"""Fault-tolerance layer tests (ISSUE 3 tentpole + satellites).

Covers the four resilience planes — retry/backoff/quarantine, heartbeat
leases + reaper, device-failure recovery, and the seeded chaos harness —
plus the crash-recovery E2E gate: kill workers mid-trial under a chaos
seed, assert the lease is reclaimed, the trial retries on another
worker, and the finished run's best trial matches a fault-free run.
"""

import glob
import os
import threading
import time

import numpy as np
import pytest

from hyperopt_tpu import Trials, fmin, hp
from hyperopt_tpu.algos import rand
from hyperopt_tpu.base import (
    JOB_STATE_DONE,
    JOB_STATE_ERROR,
    JOB_STATE_NEW,
    JOB_STATE_RUNNING,
    STATUS_OK,
)
from hyperopt_tpu.observability import FaultStats
from hyperopt_tpu.parallel.file_trials import FileJobs, FileTrials
from hyperopt_tpu.parallel.worker import FileWorker, ReserveTimeout
from hyperopt_tpu.resilience import (
    DeviceRecovery,
    LeaseReaper,
    RetryPolicy,
    SyntheticDeviceError,
    TrialQuarantined,
    TrialTimeout,
    backoff_delay,
    execute_with_retry,
    is_device_error,
    run_with_timeout,
)
from hyperopt_tpu.resilience.chaos import (
    ChaosConfig,
    ChaosMonkey,
    WorkerKilled,
    active,
)
from hyperopt_tpu.resilience.leases import LeaseHeartbeat

SPACE = {"x": hp.uniform("x", -5, 5)}


def _doc(tid):
    return {
        "tid": tid, "state": JOB_STATE_NEW, "spec": None,
        "result": {"status": "new"},
        "misc": {"tid": tid, "cmd": ("domain_attachment", "FMinIter_Domain"),
                 "idxs": {"x": [tid]}, "vals": {"x": [1.0]}},
        "exp_key": None, "owner": None, "version": 0,
        "book_time": None, "refresh_time": None,
    }


# Module-level objectives: FileTrials pickles the Domain by reference,
# so worker threads must be able to re-import these.
def quad_objective(cfg):
    return (cfg["x"] - 3.0) ** 2


def chaos_objective(cfg):
    from hyperopt_tpu.resilience import chaos

    monkey = chaos.get_active()
    if monkey is not None:
        fault = monkey.objective_fault(chaos.stable_key(cfg))
        if fault is not None:
            return fault
    return (cfg["x"] - 3.0) ** 2


_FLAKY_STATE = {"fails_left": 0}


def flaky_objective(cfg):
    if _FLAKY_STATE["fails_left"] > 0:
        _FLAKY_STATE["fails_left"] -= 1
        raise RuntimeError("transient objective failure")
    return (cfg["x"] - 3.0) ** 2


# ---------------------------------------------------------------------
# retry policy
# ---------------------------------------------------------------------

class TestRetryPolicy:
    def test_backoff_is_exponential_and_capped(self):
        p = RetryPolicy(backoff_base=0.1, backoff_multiplier=2.0,
                        backoff_max=0.5, jitter=0.0)
        assert backoff_delay(p, 1) == pytest.approx(0.1)
        assert backoff_delay(p, 2) == pytest.approx(0.2)
        assert backoff_delay(p, 3) == pytest.approx(0.4)
        assert backoff_delay(p, 4) == pytest.approx(0.5)  # capped
        assert backoff_delay(p, 10) == pytest.approx(0.5)

    def test_jitter_deterministic_and_bounded(self):
        p = RetryPolicy(backoff_base=1.0, backoff_multiplier=1.0,
                        jitter=0.2, seed=7)
        d1 = backoff_delay(p, 1, key=42)
        assert d1 == backoff_delay(p, 1, key=42)  # pure function
        assert 0.8 <= d1 <= 1.2
        assert d1 != backoff_delay(p, 1, key=43)  # decorrelated per key
        assert d1 != backoff_delay(p, 2, key=42)  # and per attempt
        p2 = RetryPolicy(backoff_base=1.0, backoff_multiplier=1.0,
                         jitter=0.2, seed=8)
        assert backoff_delay(p2, 1, key=42) != d1  # and per seed

    def test_validation(self):
        with pytest.raises(ValueError):
            RetryPolicy(max_attempts=0)
        with pytest.raises(ValueError):
            RetryPolicy(jitter=1.5)
        with pytest.raises(ValueError):
            RetryPolicy(lease_ttl=0)

    def test_json_roundtrip(self):
        p = RetryPolicy(max_attempts=5, backoff_base=0.3,
                        trial_timeout=12.5, seed=3)
        assert RetryPolicy.from_json(p.to_json()) == p

    def test_execute_with_retry_success_and_counting(self):
        stats = FaultStats()
        calls = {"n": 0}

        def fn():
            calls["n"] += 1
            if calls["n"] < 3:
                raise RuntimeError("boom")
            return "ok"

        slept = []
        result, attempts = execute_with_retry(
            fn, RetryPolicy(max_attempts=4, backoff_base=0.01, seed=1),
            key=0, stats=stats, sleep=slept.append,
        )
        assert result == "ok" and attempts == 3
        assert stats.get("trial_failure") == 2
        assert stats.get("trial_retried") == 2
        assert len(slept) == 2 and slept[1] > slept[0] * 1.5  # backoff grew

    def test_execute_with_retry_quarantines(self):
        stats = FaultStats()

        def fn():
            raise ValueError("poison")

        with pytest.raises(TrialQuarantined) as ei:
            execute_with_retry(
                fn, RetryPolicy(max_attempts=3, backoff_base=0.0),
                stats=stats, sleep=lambda s: None,
            )
        assert ei.value.attempts == 3
        assert isinstance(ei.value.last_error, ValueError)
        assert stats.get("trial_quarantined") == 1
        assert stats.get("trial_failure") == 3

    def test_first_attempt_resumes_budget(self):
        # a worker resuming a reclaimed trial starts mid-budget
        with pytest.raises(TrialQuarantined) as ei:
            execute_with_retry(
                lambda: 1 / 0, RetryPolicy(max_attempts=3),
                first_attempt=3, sleep=lambda s: None,
            )
        assert ei.value.attempts == 3  # no retries left

    def test_run_with_timeout(self):
        stats = FaultStats()
        assert run_with_timeout(lambda: 5, 1.0) == 5
        assert run_with_timeout(lambda: 5, None) == 5
        with pytest.raises(TrialTimeout):
            run_with_timeout(lambda: time.sleep(5), 0.05, stats=stats)
        assert stats.get("objective_timeout") == 1
        with pytest.raises(KeyError):  # errors delivered, not swallowed
            run_with_timeout(lambda: {}["missing"], 1.0)


# ---------------------------------------------------------------------
# leases
# ---------------------------------------------------------------------

class TestLeases:
    def test_reserve_grants_lease_and_counts_attempts(self, tmp_path):
        jobs = FileJobs(str(tmp_path), lease_ttl=5.0)
        jobs.insert(_doc(0))
        job = jobs.reserve("w1")
        assert job["misc"]["attempts"] == 1
        lease = jobs.read_lease(0)
        assert lease["owner"] == "w1" and lease["attempt"] == 1
        assert lease["expires_at"] > time.time()

    def test_renew_is_owner_checked(self, tmp_path):
        jobs = FileJobs(str(tmp_path), lease_ttl=5.0)
        jobs.insert(_doc(0))
        jobs.reserve("w1")
        before = jobs.read_lease(0)["expires_at"]
        time.sleep(0.02)
        assert jobs.renew_lease(0, "w1")
        assert jobs.read_lease(0)["expires_at"] > before
        assert not jobs.renew_lease(0, "impostor")
        jobs.clear_lease(0)
        assert not jobs.renew_lease(0, "w1")  # gone

    def test_torn_lease_reads_as_none(self, tmp_path):
        jobs = FileJobs(str(tmp_path))
        with open(jobs.lease_path(3), "wb") as f:
            f.write(b"\x00garbage")
        assert jobs.read_lease(3) is None

    def test_reaper_reclaims_expired_lease(self, tmp_path):
        jobs_ttl = 0.2
        trials = FileTrials(str(tmp_path), lease_ttl=jobs_ttl)
        trials.jobs.insert(_doc(0))
        trials.jobs.reserve("dead")  # never heartbeats
        stats = FaultStats()
        reaper = LeaseReaper(
            trials, policy=RetryPolicy(max_attempts=3, lease_ttl=jobs_ttl),
            stats=stats,
        )
        assert reaper.reap_once() == 0  # lease still fresh
        time.sleep(0.3)
        assert reaper.reap_once() == 1
        doc = trials.jobs.read_doc(0)
        assert doc["state"] == JOB_STATE_NEW
        assert doc["owner"] is None
        assert doc["misc"]["attempts"] == 1  # attempt was consumed
        assert not os.path.exists(trials.jobs.lock_path(0))
        assert trials.jobs.read_lease(0) is None
        assert stats.get("lease_reclaimed") == 1
        # the trial is re-reservable, and the attempt counter advances
        job = trials.jobs.reserve("w2")
        assert job is not None and job["misc"]["attempts"] == 2

    def test_reaper_quarantines_after_max_attempts(self, tmp_path):
        trials = FileTrials(str(tmp_path), lease_ttl=0.15)
        trials.jobs.insert(_doc(0))
        stats = FaultStats()
        reaper = LeaseReaper(
            trials, policy=RetryPolicy(max_attempts=2, lease_ttl=0.15),
            stats=stats,
        )
        for expected_state in (JOB_STATE_NEW, JOB_STATE_ERROR):
            assert trials.jobs.reserve("dead") is not None
            time.sleep(0.25)
            assert reaper.reap_once() == 1
            assert trials.jobs.read_doc(0)["state"] == expected_state
        doc = trials.jobs.read_doc(0)
        assert doc["misc"]["error"][0] == "LeaseExpired"
        assert stats.get("lease_quarantined") == 1
        assert stats.get("lease_reclaimed") == 1

    def test_reaper_leaves_completed_doc_alone(self, tmp_path):
        # the worker finished inside the reaper's scan window: its DONE
        # result must survive
        trials = FileTrials(str(tmp_path), lease_ttl=0.1)
        trials.jobs.insert(_doc(0))
        job = trials.jobs.reserve("slow")
        time.sleep(0.2)
        job["state"] = JOB_STATE_DONE
        job["result"] = {"status": STATUS_OK, "loss": 1.0}
        trials.jobs.write(job)
        reaper = LeaseReaper(trials, policy=RetryPolicy(lease_ttl=0.1))
        reaper.reap_once()
        assert trials.jobs.read_doc(0)["state"] == JOB_STATE_DONE

    def test_reaper_clears_stale_lock_on_new_trial(self, tmp_path):
        # a worker died between lock create and doc rewrite (or chaos
        # tore the lock): the NEW trial must become reservable again
        trials = FileTrials(str(tmp_path), lease_ttl=0.1)
        trials.jobs.insert(_doc(0))
        with open(trials.jobs.lock_path(0), "wb") as f:
            f.write(b"\x00torn\x00")
        assert trials.jobs.reserve("w1") is None  # blocked
        stats = FaultStats()
        reaper = LeaseReaper(
            trials, policy=RetryPolicy(lease_ttl=0.1), stats=stats
        )
        time.sleep(0.2)
        reaper.reap_once()
        assert stats.get("stale_lock_cleared") == 1
        assert trials.jobs.reserve("w1") is not None  # unblocked

    def test_heartbeat_keeps_lease_alive_then_lost_on_reclaim(self, tmp_path):
        ttl = 0.25
        trials = FileTrials(str(tmp_path), lease_ttl=ttl)
        trials.jobs.insert(_doc(0))
        trials.jobs.reserve("w1")
        stats = FaultStats()
        hb = LeaseHeartbeat(trials.jobs, 0, "w1", ttl=ttl,
                            interval=0.05, stats=stats).start()
        try:
            reaper = LeaseReaper(trials, policy=RetryPolicy(lease_ttl=ttl))
            time.sleep(2.5 * ttl)  # well past the ttl — but heartbeating
            assert reaper.reap_once() == 0
            assert trials.jobs.read_doc(0)["state"] == JOB_STATE_RUNNING
            assert stats.get("heartbeat") >= 3
            # now the reaper wins (simulate: lease cleared under us)
            trials.jobs.clear_lease(0)
            time.sleep(0.15)
            assert hb.lost
        finally:
            hb.stop()

    def test_worker_drops_stale_result(self, tmp_path):
        # lease reclaimed while the worker evaluates -> its result is
        # dropped, not written over the re-queued trial
        jobs = FileJobs(str(tmp_path), lease_ttl=5.0)
        jobs.insert(_doc(0))
        worker = FileWorker(str(tmp_path), poll_interval=0.01,
                            retry_policy=None)
        job = jobs.reserve("someone-else-came-first")  # simulate reclaim+steal
        assert job is not None
        stats = worker.stats
        hb = LeaseHeartbeat(jobs, 0, worker.owner, ttl=5.0, interval=10.0)
        wrote = worker._finish(
            dict(job, state=JOB_STATE_DONE,
                 result={"status": STATUS_OK, "loss": 0.0}),
            hb, worker.owner,
        )
        assert wrote is False
        assert stats.get("stale_result_dropped") == 1
        assert jobs.read_doc(0)["state"] == JOB_STATE_RUNNING  # untouched


# ---------------------------------------------------------------------
# worker retry integration
# ---------------------------------------------------------------------

class TestWorkerRetry:
    def test_worker_retries_in_place_from_attachment_policy(self, tmp_path):
        from hyperopt_tpu.base import Domain
        import pickle

        trials = FileTrials(str(tmp_path), lease_ttl=5.0)
        trials.attachments["FMinIter_Domain"] = pickle.dumps(
            Domain(flaky_objective, SPACE)
        )
        trials.attachments["FMinIter_RetryPolicy"] = RetryPolicy(
            max_attempts=4, backoff_base=0.01, backoff_max=0.02
        ).to_json()
        trials.jobs.insert(_doc(0))
        _FLAKY_STATE["fails_left"] = 2
        worker = FileWorker(str(tmp_path), poll_interval=0.01)
        job = worker.run_one(reserve_timeout=1.0)
        assert job["state"] == JOB_STATE_DONE
        assert job["misc"]["attempts"] == 3  # 2 failures + 1 success
        assert worker.stats.get("trial_retried") == 2
        doc = trials.jobs.read_doc(0)
        assert doc["state"] == JOB_STATE_DONE
        assert doc["misc"]["attempts"] == 3

    def test_worker_adopts_policy_lease_ttl_and_follows_updates(self, tmp_path):
        trials = FileTrials(str(tmp_path))
        trials.attachments["FMinIter_RetryPolicy"] = RetryPolicy(
            lease_ttl=7.5
        ).to_json()
        worker = FileWorker(str(tmp_path), poll_interval=0.01)
        assert worker._retry_policy().lease_ttl == 7.5
        assert worker.trials.jobs.lease_ttl == 7.5  # adopted
        # a NEW driver run republishes a different policy: the same
        # long-lived worker follows it (blob-compare cache, not load-once)
        trials.attachments["FMinIter_RetryPolicy"] = RetryPolicy(
            lease_ttl=3.0, max_attempts=9
        ).to_json()
        assert worker._retry_policy().max_attempts == 9
        assert worker.trials.jobs.lease_ttl == 3.0
        # a run without a policy clears the attachment -> no retries
        del trials.attachments["FMinIter_RetryPolicy"]
        assert worker._retry_policy() is None
        # an explicit --lease-ttl always wins over the attachment
        explicit = FileWorker(str(tmp_path), poll_interval=0.01,
                              lease_ttl=42.0)
        trials.attachments["FMinIter_RetryPolicy"] = RetryPolicy(
            lease_ttl=7.5
        ).to_json()
        assert explicit._retry_policy().lease_ttl == 7.5
        assert explicit.trials.jobs.lease_ttl == 42.0

    def test_worker_quarantines_after_budget(self, tmp_path):
        from hyperopt_tpu.base import Domain
        import pickle

        trials = FileTrials(str(tmp_path), lease_ttl=5.0)
        trials.attachments["FMinIter_Domain"] = pickle.dumps(
            Domain(flaky_objective, SPACE)
        )
        trials.attachments["FMinIter_RetryPolicy"] = RetryPolicy(
            max_attempts=2, backoff_base=0.01
        ).to_json()
        trials.jobs.insert(_doc(0))
        _FLAKY_STATE["fails_left"] = 99
        worker = FileWorker(str(tmp_path), poll_interval=0.01)
        with pytest.raises(TrialQuarantined):
            worker.run_one(reserve_timeout=1.0)
        _FLAKY_STATE["fails_left"] = 0
        doc = trials.jobs.read_doc(0)
        assert doc["state"] == JOB_STATE_ERROR
        # terminal write released the reservation
        assert not os.path.exists(trials.jobs.lock_path(0))
        assert trials.jobs.read_lease(0) is None


class TestWorkerCLI:
    def test_last_job_timeout_caps_the_reserve_wait(self, tmp_path):
        # an empty queue + huge --reserve-timeout must still exit at the
        # --last-job-timeout deadline (previously the reserve wait could
        # overshoot it by a full reserve_timeout)
        from hyperopt_tpu.parallel.worker import main_worker_helper, make_parser

        opts = make_parser().parse_args([
            "--queue", str(tmp_path),
            "--poll-interval", "0.02",
            "--reserve-timeout", "300",
            "--last-job-timeout", "0.3",
        ])
        t0 = time.time()
        assert main_worker_helper(opts) == 0
        assert time.time() - t0 < 5.0

    def test_max_consecutive_failures_exits_nonzero(self, tmp_path):
        from hyperopt_tpu.base import Domain
        import pickle

        trials = FileTrials(str(tmp_path))
        trials.attachments["FMinIter_Domain"] = pickle.dumps(
            Domain(flaky_objective, SPACE)
        )
        for tid in range(3):
            trials.jobs.insert(_doc(tid))
        _FLAKY_STATE["fails_left"] = 99
        from hyperopt_tpu.parallel.worker import main_worker_helper, make_parser

        opts = make_parser().parse_args([
            "--queue", str(tmp_path),
            "--poll-interval", "0.02",
            "--reserve-timeout", "0.2",
            "--max-consecutive-failures", "2",
        ])
        try:
            assert main_worker_helper(opts) == 1
        finally:
            _FLAKY_STATE["fails_left"] = 0

    def test_lease_ttl_flag(self):
        from hyperopt_tpu.parallel.worker import make_parser

        opts = make_parser().parse_args(["--queue", "q", "--lease-ttl", "7.5"])
        assert opts.lease_ttl == 7.5


# ---------------------------------------------------------------------
# device recovery
# ---------------------------------------------------------------------

class TestDeviceRecovery:
    def test_is_device_error(self):
        assert is_device_error(SyntheticDeviceError("x"))
        assert not is_device_error(ValueError("x"))
        e = ValueError("tagged")
        e._hyperopt_device_error = True
        assert is_device_error(e)

    def test_transient_error_reinits_and_recovers(self):
        stats = FaultStats()
        rec = DeviceRecovery(max_reinits=2, stats=stats)
        calls = {"n": 0}

        def fn():
            calls["n"] += 1
            if calls["n"] == 1:
                raise SyntheticDeviceError("preempted")
            return "suggestion"

        assert rec.run(fn) == "suggestion"
        assert stats.get("device_error") == 1
        assert stats.get("device_reinit") == 1
        assert not rec.cpu_fallback_active

    def test_success_refills_consecutive_budget(self):
        rec = DeviceRecovery(max_reinits=1, stats=FaultStats())
        flaky = {"fail_next": True}

        def fn():
            if flaky["fail_next"]:
                flaky["fail_next"] = False
                raise SyntheticDeviceError("blip")
            return 1

        for _ in range(4):  # 4 scattered single faults, each recovers
            flaky["fail_next"] = True
            assert rec.run(fn) == 1
        assert not rec.cpu_fallback_active
        assert rec.n_reinits == 0  # refilled after each success

    def test_persistent_failure_escalates_to_cpu_then_raises(self):
        stats = FaultStats()
        rec = DeviceRecovery(max_reinits=1, stats=stats)

        def fn():
            raise SyntheticDeviceError("dead device")

        with pytest.raises(SyntheticDeviceError):
            rec.run(fn)
        assert stats.get("device_reinit") == 1
        assert stats.get("cpu_fallback") == 1
        assert rec.cpu_fallback_active
        assert stats.get("device_error") == 3  # reinit + cpu + exhausted

    def test_non_device_error_passes_through(self):
        rec = DeviceRecovery(stats=FaultStats())
        with pytest.raises(KeyError):
            rec.run(lambda: {}["missing"])
        assert rec.stats.get("device_error") == 0

    def test_absorb_contract(self):
        rec = DeviceRecovery(max_reinits=1, stats=FaultStats())
        assert rec.absorb(ValueError("not a device error")) is None
        assert rec.absorb(SyntheticDeviceError("a")) is True  # reinit
        assert rec.absorb(SyntheticDeviceError("b")) is True  # cpu
        assert rec.absorb(SyntheticDeviceError("c")) is False  # exhausted


# ---------------------------------------------------------------------
# chaos harness
# ---------------------------------------------------------------------

class TestChaos:
    def test_schedule_is_seed_deterministic(self):
        cfg = ChaosConfig(seed=5, p_worker_kill=0.5)

        def schedule(monkey):
            out = []
            for tid in range(50):
                try:
                    monkey.maybe_kill_worker(tid, "pre")
                    out.append(False)
                except WorkerKilled:
                    out.append(True)
            return out

        s1 = schedule(ChaosMonkey(cfg))
        s2 = schedule(ChaosMonkey(cfg))
        assert s1 == s2 and any(s1) and not all(s1)
        s3 = schedule(ChaosMonkey(ChaosConfig(seed=6, p_worker_kill=0.5)))
        assert s3 != s1

    def test_occurrence_advances_so_retries_reroll(self):
        monkey = ChaosMonkey(ChaosConfig(seed=0, p_objective_error=1.0))
        from hyperopt_tpu.resilience.chaos import ChaosObjectiveError

        with pytest.raises(ChaosObjectiveError):
            monkey.objective_fault("k")
        # occurrence advanced — p=1 still fires, but the roll is distinct
        assert monkey._occurrence[("objective_error", "k")] == 1
        assert monkey.stats.get("chaos_objective_error") == 1

    def test_injections_are_counted(self):
        monkey = ChaosMonkey(ChaosConfig(seed=0, p_objective_nan=1.0))
        out = monkey.objective_fault("k")
        assert out != out  # NaN
        assert monkey.stats.injected() == {"objective_nan": 1}

    def test_activation_is_exclusive_and_scoped(self):
        from hyperopt_tpu.resilience import chaos

        m = ChaosMonkey(ChaosConfig(seed=0))
        assert chaos.get_active() is None
        with active(m):
            assert chaos.get_active() is m
            with pytest.raises(RuntimeError):
                with active(ChaosMonkey(ChaosConfig(seed=1))):
                    pass
        assert chaos.get_active() is None

    def test_device_observer_installed_only_when_configured(self):
        from hyperopt_tpu.algos import tpe_device

        n0 = len(tpe_device._suggest_observers)
        with active(ChaosMonkey(ChaosConfig(seed=0))):
            assert len(tpe_device._suggest_observers) == n0
        with active(ChaosMonkey(ChaosConfig(seed=0, p_device_error=0.5))):
            assert len(tpe_device._suggest_observers) == n0 + 1
        assert len(tpe_device._suggest_observers) == n0


# ---------------------------------------------------------------------
# crash-recovery E2E (the satellite gate)
# ---------------------------------------------------------------------

def _supervised_workers(qdir, n_workers, lease_ttl, stats):
    """Respawning worker-thread slots (a killed worker is replaced)."""
    stop = threading.Event()

    def supervise():
        while not stop.is_set():
            worker = FileWorker(qdir, poll_interval=0.02,
                                lease_ttl=lease_ttl, stats=stats)
            try:
                while not stop.is_set():
                    try:
                        worker.run_one(reserve_timeout=0.3)
                    except ReserveTimeout:
                        continue
            except WorkerKilled:
                continue  # respawn a fresh "process"
            except Exception:
                time.sleep(0.05)

    threads = [threading.Thread(target=supervise, daemon=True)
               for _ in range(n_workers)]
    for t in threads:
        t.start()
    return threads, stop


def _run_filetrials_fmin(qdir, n_trials, seed, lease_ttl, policy, stats,
                         n_workers=2):
    trials = FileTrials(qdir, lease_ttl=lease_ttl)
    threads, stop = _supervised_workers(qdir, n_workers, lease_ttl, stats)
    try:
        fmin(chaos_objective, SPACE, algo=rand.suggest,
             max_evals=n_trials, trials=trials,
             rstate=np.random.default_rng(seed),
             retry_policy=policy, fault_stats=stats,
             show_progressbar=False, verbose=False)
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=5.0)
    trials.refresh()
    return trials


def _best_ok(trials):
    best = None
    for t in trials.trials:
        if t["state"] != JOB_STATE_DONE:
            continue
        loss = t["result"].get("loss")
        if t["result"].get("status") != STATUS_OK or loss is None:
            continue
        if best is None or loss < best[1]:
            best = (t["tid"], float(loss))
    return best


def test_crash_recovery_e2e_matches_fault_free(tmp_path):
    """Kill workers mid-trial under a chaos seed: leases are reclaimed,
    trials retry on respawned workers, the run completes with zero
    stranded reservations, and the best trial equals the fault-free
    run's best on the same seed."""
    # max_attempts=6: each attempt rolls the kill site twice (pre+post,
    # ~0.36 combined at p=0.2), so a 4-attempt budget quarantines a tid
    # every few seeds; six absorbs any realistic kill streak
    n_trials, seed, lease_ttl = 20, 0, 0.4
    policy = RetryPolicy(max_attempts=6, backoff_base=0.02,
                         backoff_max=0.1, lease_ttl=lease_ttl, seed=seed)

    ff = _run_filetrials_fmin(str(tmp_path / "ff"), n_trials, seed,
                              lease_ttl, policy, FaultStats())
    ff_best = _best_ok(ff)

    stats = FaultStats()
    monkey = ChaosMonkey(ChaosConfig(seed=seed, p_worker_kill=0.2),
                         stats=stats)
    with active(monkey):
        ch = _run_filetrials_fmin(str(tmp_path / "chaos"), n_trials, seed,
                                  lease_ttl, policy, stats)

    kills = stats.injected().get("worker_kill", 0)
    assert kills >= 1, "chaos schedule injected no kills; raise p or seed"
    # every kill left a RUNNING trial whose lease had to be reclaimed
    # (or quarantined) for fmin to have returned at all
    assert (stats.get("lease_reclaimed")
            + stats.get("lease_quarantined")) >= kills
    # zero stranded reservations
    docs = ch.jobs.all_docs()
    assert sum(1 for d in docs if d["state"] == JOB_STATE_RUNNING) == 0
    assert ch.jobs.locked_tids() == []
    assert len(glob.glob(os.path.join(ch.jobs.root, "leases", "*"))) == 0
    # all trials completed (none quarantined at this kill rate/budget)
    assert sum(1 for d in docs if d["state"] == JOB_STATE_DONE) == n_trials
    # identical best trial (rand suggestions are result-independent, and
    # retried trials re-evaluate the same deterministic point)
    assert _best_ok(ch) == ff_best


def test_device_chaos_trajectory_is_seed_transparent():
    """Synthetic device errors at suggest dispatch: the recovered TPE
    run's parameter stream and best trial equal the fault-free run's
    (failed launches re-use their drawn (ids, seed))."""
    from hyperopt_tpu.algos import tpe

    def run(with_chaos):
        trials = Trials()
        stats = FaultStats()

        def _go():
            fmin(chaos_objective, SPACE, algo=tpe.suggest, max_evals=26,
                 trials=trials, rstate=np.random.default_rng(1),
                 retry_policy=RetryPolicy(max_attempts=3, backoff_base=0.01),
                 fault_stats=stats, show_progressbar=False, verbose=False)

        if with_chaos:
            monkey = ChaosMonkey(
                ChaosConfig(seed=2, p_device_error=0.3), stats=stats
            )
            with active(monkey):
                _go()
        else:
            _go()
        return trials, stats

    ff_trials, _ = run(False)
    ch_trials, stats = run(True)
    assert stats.injected().get("device_error", 0) >= 1
    assert stats.get("device_error") >= stats.injected()["device_error"]
    assert len(ch_trials.trials) == len(ff_trials.trials)
    for a, b in zip(ch_trials.trials, ff_trials.trials):
        assert a["misc"]["vals"] == b["misc"]["vals"]
    assert _best_ok(ch_trials) == _best_ok(ff_trials)


def test_fmin_quarantine_keeps_run_alive():
    """A permanently failing point is quarantined, not fatal, and the
    error trial is excluded from the history the TPE fit reads."""
    seen = {}

    def sometimes_poison(cfg):
        # the third DISTINCT point fails on every attempt (a genuinely
        # poison trial — retries must not rescue it)
        x = cfg["x"]
        seen.setdefault(x, len(seen))
        if seen[x] == 2:
            raise RuntimeError("poison point")
        return (x - 3.0) ** 2

    trials = Trials()
    fmin(sometimes_poison, SPACE, algo=rand.suggest, max_evals=6,
         trials=trials, rstate=np.random.default_rng(0),
         retry_policy=RetryPolicy(max_attempts=2, backoff_base=0.0),
         show_progressbar=False, verbose=False)
    # Trials.trials filters JOB_STATE_ERROR out (reference semantics —
    # that filter IS the "excluded from the fit" mechanism); the
    # quarantined doc lives on in the full dynamic list
    states = [t["state"] for t in trials._dynamic_trials]
    assert states.count(JOB_STATE_ERROR) == 1
    assert states.count(JOB_STATE_DONE) == 5
    assert [t["state"] for t in trials.trials] == [JOB_STATE_DONE] * 5
    err = next(t for t in trials._dynamic_trials
               if t["state"] == JOB_STATE_ERROR)
    assert err["misc"]["attempts"] == 2
    assert "poison" in err["misc"]["error"][1]
    # quarantined trial contributes no loss to the history/fit
    assert len(trials.history.losses) == 5


def test_delayed_result_past_ttl_is_dropped_and_trial_retries(tmp_path):
    """The result_delay chaos site models a frozen worker (heartbeat
    stalls with it): past the TTL the reaper reclaims, the late write is
    dropped, and the trial completes on a retry."""
    import pickle

    from hyperopt_tpu.base import Domain

    ttl = 0.25
    trials = FileTrials(str(tmp_path), lease_ttl=ttl)
    trials.attachments["FMinIter_Domain"] = pickle.dumps(
        Domain(quad_objective, SPACE)
    )
    trials.jobs.insert(_doc(0))
    stats = FaultStats()
    monkey = ChaosMonkey(
        ChaosConfig(seed=0, p_result_delay=1.0, delay_seconds=3 * ttl),
        stats=stats,
    )
    reaper = LeaseReaper(
        trials, policy=RetryPolicy(max_attempts=3, lease_ttl=ttl),
        stats=stats, interval=ttl / 4,
    )
    worker = FileWorker(str(tmp_path), poll_interval=0.01, lease_ttl=ttl,
                        stats=stats)
    with reaper:
        with active(monkey):
            job = worker.run_one(reserve_timeout=1.0)  # stalls, gets reclaimed
        assert stats.get("chaos_result_delay") == 1
        assert stats.get("stale_result_dropped") == 1
        assert job["tid"] == 0
        # the reaper re-queued it; a healthy worker (chaos off) finishes it
        deadline = time.time() + 5.0
        while time.time() < deadline:
            if trials.jobs.read_doc(0)["state"] == JOB_STATE_NEW:
                break
            time.sleep(0.05)
        job2 = worker.run_one(reserve_timeout=2.0)
    doc = trials.jobs.read_doc(0)
    assert doc["state"] == JOB_STATE_DONE
    assert doc["misc"]["attempts"] == 2
    assert job2["result"] == job["result"]  # deterministic objective


def test_jax_trials_retry_policy_is_honored():
    """retry_policy must reach JaxTrials' dispatcher threads: flaky
    objectives retry, poison ones quarantine, and the run survives."""
    from hyperopt_tpu.parallel.jax_trials import JaxTrials

    attempts_by_x = {}

    def flaky(cfg):
        x = cfg["x"]
        n = attempts_by_x.get(x, 0) + 1
        attempts_by_x[x] = n
        if n == 1:  # every point fails its first attempt
            raise RuntimeError("transient")
        return (x - 3.0) ** 2

    stats = FaultStats()
    trials = JaxTrials(parallelism=2)
    best = fmin(flaky, SPACE, algo=rand.suggest, max_evals=8, trials=trials,
                rstate=np.random.default_rng(0),
                retry_policy=RetryPolicy(max_attempts=3, backoff_base=0.01),
                fault_stats=stats,
                show_progressbar=False, verbose=False)
    assert best is not None
    done = [t for t in trials._dynamic_trials if t["state"] == JOB_STATE_DONE]
    assert len(done) == 8
    assert all(t["misc"]["attempts"] == 2 for t in done)
    assert stats.get("trial_retried") == 8


def test_fault_stats_merge_and_summary():
    a, b = FaultStats(), FaultStats()
    a.record("lease_reclaimed", 2)
    a.record_backoff(0.5)
    b.record("lease_reclaimed")
    b.record("chaos_worker_kill", 3)
    a.merge(b)
    assert a.get("lease_reclaimed") == 3
    assert a.injected() == {"worker_kill": 3}
    s = a.summary()
    assert s["backoff_s"] == 0.5 and s["chaos_worker_kill"] == 3


# ---------------------------------------------------------------------
# service-plane chaos sites (ISSUE 5)
# ---------------------------------------------------------------------


class TestServicePlaneChaosSites:
    def _cfg(self, **kw):
        from hyperopt_tpu.resilience.chaos import ChaosConfig

        base = dict(
            seed=3, p_conn_reset_pre=0.4, p_conn_reset_post=0.4,
            p_server_kill=0.3, p_slow_loris=0.5,
            p_torn_doc=1.0, p_torn_journal=1.0,
            tear_kills_process=False,  # unit tests must outlive a tear
        )
        base.update(kw)
        return ChaosConfig(**base)

    def test_rolls_are_deterministic_in_seed(self):
        from hyperopt_tpu.resilience.chaos import ChaosMonkey

        def sequence():
            m = ChaosMonkey(self._cfg())
            return (
                [m.should_reset_connection("suggest", "s", "pre")
                 for _ in range(10)]
                + [m.should_reset_connection("report", "s", "post")
                   for _ in range(10)]
                + [m.should_kill_server("extra") for _ in range(10)]
                + [m.should_slow_loris("tick") for _ in range(10)]
            )

        first, second = sequence(), sequence()
        assert first == second  # pure fn of (seed, site, key, occurrence)
        assert any(first) and not all(first)
        # a different seed gives a different schedule
        from hyperopt_tpu.resilience.chaos import ChaosMonkey as M

        other = M(self._cfg(seed=4))
        assert [
            other.should_reset_connection("suggest", "s", "pre")
            for _ in range(10)
        ] != first[:10]

    def test_torn_doc_detected_by_crc(self, tmp_path):
        from hyperopt_tpu.parallel.file_trials import (
            _encode_doc,
            _read_doc,
        )
        from hyperopt_tpu.resilience.chaos import ChaosMonkey

        path = str(tmp_path / "000000000007.json")
        with open(path, "wb") as f:
            f.write(_encode_doc({"tid": 7, "state": 0}))
        m = ChaosMonkey(self._cfg())
        m.maybe_torn_doc(path, 7)
        assert m.stats.get("chaos_torn_doc") == 1
        # torn in place: quarantined on read, not parsed as garbage
        assert _read_doc(path) is None
        assert not os.path.exists(path)
        assert os.path.exists(path + ".corrupt")

    def test_torn_journal_loses_only_the_tail(self, tmp_path):
        from hyperopt_tpu.resilience.chaos import ChaosMonkey
        from hyperopt_tpu.service.core import ResponseJournal

        path = str(tmp_path / "journal.jsonl")
        j = ResponseJournal(path=path)
        j.record("a", "report", b"{}", tid=0, result={"status": "ok"})
        j.record("b", "report", b"{}", tid=1, result={"status": "ok"})
        m = ChaosMonkey(self._cfg())
        m.maybe_torn_journal(path, "b")
        j2 = ResponseJournal(path=path)
        assert j2.n_torn_lines == 1
        assert j2.get("a") is not None  # acknowledged entry survives
        assert j2.get("b") is None  # only the torn tail record is lost

    def test_injection_log_survives_and_counts(self, tmp_path):
        from hyperopt_tpu.resilience.chaos import ChaosMonkey

        log = str(tmp_path / "inj.jsonl")
        m = ChaosMonkey(self._cfg(injection_log=log, p_slow_loris=1.0))
        for _ in range(3):
            assert m.should_slow_loris("t")
        from hyperopt_tpu.resilience.chaos import parse_injection_log

        with open(log, "rb") as f:
            recs = parse_injection_log(f.read())
        assert len(recs) == 3
        assert {r["site"] for r in recs} == {"slow_loris"}
        assert [r["occurrence"] for r in recs] == [0, 1, 2]

    def test_config_json_roundtrip(self):
        from hyperopt_tpu.resilience.chaos import ChaosConfig

        cfg = self._cfg(injection_log="/tmp/x.jsonl")
        again = ChaosConfig.from_json(cfg.to_json())
        assert again == cfg


class TestCircuitBreakerUnits:
    def test_reopen_after_failed_probe(self):
        from hyperopt_tpu.resilience.retry import CircuitBreaker

        clock = [0.0]
        b = CircuitBreaker(threshold=1, cooldown=5.0,
                           clock=lambda: clock[0])
        b.record_failure()
        assert b.state == "open"
        clock[0] = 5.1
        assert b.before_request() == 0.0  # the probe
        b.record_failure()  # probe failed: re-open from NOW
        assert b.state == "open"
        assert b.before_request() == pytest.approx(5.0)

    def test_threshold_validated(self):
        from hyperopt_tpu.resilience.retry import CircuitBreaker

        with pytest.raises(ValueError):
            CircuitBreaker(threshold=0)


# ---------------------------------------------------------------------
# race-lint gate for the new locks (satellite)
# ---------------------------------------------------------------------

def test_resilience_package_passes_race_lint():
    from hyperopt_tpu.analysis import discover_race_files, lint_races

    paths = [p for p in discover_race_files()
             if os.sep + "resilience" + os.sep in p]
    # leases, device, chaos + (ISSUE 5) retry's client circuit breaker
    # are all auto-discovered (ISSUE 12: the hand registry is gone)
    assert {"leases.py", "device.py", "chaos.py", "retry.py"} <= {
        os.path.basename(p) for p in paths
    }
    diags = lint_races(paths)
    assert diags == [], [d.format() for d in diags]


# ---------------------------------------------------------------------
# replica-plane ownership leases (ISSUE 13 satellite): cross-process
# claim contention, fencing-token monotonicity, stale-write drops
# ---------------------------------------------------------------------

_HELPER = os.path.join(os.path.dirname(__file__), "replica_lease_helper.py")


def _spawn_helper(*args):
    import subprocess
    import sys

    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    return subprocess.Popen(
        [sys.executable, _HELPER, *map(str, args)],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, env=env,
    )


class TestReplicaLeases:
    def test_cross_process_claim_race_has_one_winner(self, tmp_path):
        """Two REAL processes race one claim on the same study: the
        O_CREAT|O_EXCL claim lock must admit exactly one winner."""
        import json

        root = str(tmp_path)
        procs = [
            _spawn_helper(root, "contested", f"racer-{i}", "race")
            for i in range(2)
        ]
        time.sleep(1.0)  # both parked on the go file (imports done)
        with open(os.path.join(root, "go"), "w") as f:
            f.write("go")
        results = []
        for p in procs:
            out, err = p.communicate(timeout=60)
            assert p.returncode == 0, err.decode()
            results.append(json.loads(out.decode().strip()))
        fences = [r["fence"] for r in results]
        winners = [f for f in fences if f is not None]
        assert len(winners) == 1, results
        assert winners[0] == 1

    def test_fencing_tokens_monotonic_across_processes(self, tmp_path):
        """Two processes interleaving claim→release cycles: every claim
        bumps the fence, no token is ever reused, and each process sees
        a strictly increasing sequence."""
        import json

        root = str(tmp_path)
        n = 5
        procs = [
            _spawn_helper(root, "shared", f"cycler-{i}", "cycle", n)
            for i in range(2)
        ]
        all_fences = []
        for p in procs:
            out, err = p.communicate(timeout=120)
            assert p.returncode == 0, err.decode()
            rec = json.loads(out.decode().strip())
            assert "fences" in rec, rec
            seq = rec["fences"]
            assert seq == sorted(seq) and len(set(seq)) == len(seq), seq
            all_fences.extend(seq)
        # no reuse across processes, and every claim bumped exactly once
        assert len(set(all_fences)) == 2 * n, all_fences
        assert max(all_fences) == 2 * n

    def test_expired_lease_is_reclaimable_and_old_fence_dies(self, tmp_path):
        from hyperopt_tpu.service.replicas import StudyLeaseStore

        store = StudyLeaseStore(str(tmp_path), ttl=0.2)
        f1 = store.claim("s", "r1")
        assert f1 == 1
        # frozen holder: no renewals past the TTL
        time.sleep(0.3)
        f2 = store.claim("s", "r2")
        assert f2 == 2
        # the resumed holder's credential is dead: verify fails, renew
        # fails, and a re-claim while r2 is live fails
        assert not store.verify("s", "r1", f1)
        assert not store.renew("s", "r1", f1)
        assert store.claim("s", "r1") is None
        # r2's own credential is current
        assert store.verify("s", "r2", f2)

    def test_torn_lease_never_resets_the_fence(self, tmp_path):
        """A torn lease file reads as 'no grant' but the separate fence
        counter keeps tokens monotonic — the stale holder still loses."""
        from hyperopt_tpu.service.replicas import StudyLeaseStore

        store = StudyLeaseStore(str(tmp_path), ttl=60.0)
        f1 = store.claim("s", "r1")
        # tear the lease file in place (lying-disk model)
        path = store.lease_path("s")
        with open(path, "r+b") as f:
            f.truncate(os.path.getsize(path) // 2)
        assert store.read("s") is None
        f2 = store.claim("s", "r2")
        assert f2 == f1 + 1  # the fence counter survived the tear
        assert not store.verify("s", "r1", f1)

    def test_stale_fenced_terminal_write_dropped_end_to_end(self, tmp_path):
        """The PR 3 owner-re-verify discipline one level up: a frozen
        replica whose study was reclaimed has its terminal report
        DROPPED at the commit-time fence verify — nothing lands in the
        journal or the store, and the service redirects."""
        from hyperopt_tpu.service import NotOwner, OptimizationService

        root = str(tmp_path / "root")
        algo_params = {"n_startup_jobs": 2, "n_EI_candidates": 8}
        s1 = OptimizationService(
            root=root, replica_id="r1", advertise_url="http://r1",
            replica_ttl=0.4, batch_window=0.001, warmup=False,
        )
        s2 = None
        try:
            s1.create_study("mig", SPACE, seed=3, algo="tpe",
                            algo_params=algo_params)
            (t1,) = s1.suggest("mig")
            s1.report("mig", t1["tid"], loss=1.0)
            (t2,) = s1.suggest("mig")
            # freeze r1: heartbeats stop, lease left in place to expire
            s1.replica_set._stop.set()
            time.sleep(0.6)
            # r2 starts on the shared root and reclaims the study at
            # startup recovery (expired lease -> bumped fence)
            s2 = OptimizationService(
                root=root, replica_id="r2", advertise_url="http://r2",
                replica_ttl=0.4, batch_window=0.001, warmup=False,
            )
            assert "mig" in s2.registry.list()
            h1 = s1.replica_set.handle_of("mig")
            h2 = s2.replica_set.handle_of("mig")
            assert h2.fence > h1.fence
            # the frozen replica resumes and tries to land t2's loss:
            # dropped BEFORE any journal/store mutation, and the
            # service answers NotOwner (the 307/503 shape)
            with pytest.raises(NotOwner):
                s1.report("mig", t2["tid"], loss=0.5)
            assert s1.replica_set.stats.get("stale_write_dropped") >= 1
            # nothing landed: r2's copy of t2 is still un-reported
            status = s2.study_status("mig")
            assert status["n_completed"] == 1
            # ... and r2 lands it fine (the client's retry path)
            s2.report("mig", t2["tid"], loss=0.5)
            assert s2.study_status("mig")["n_completed"] == 2
        finally:
            s1.close()
            if s2 is not None:
                s2.close()
