"""Criteria numerics vs closed forms (reference parity: test_criteria.py)."""

import numpy as np
import pytest

from hyperopt_tpu.algos import criteria


def test_ei_empirical():
    samples = np.array([0.0, 1.0, 2.0, 3.0])
    assert criteria.EI_empirical(samples, 1.0) == pytest.approx((0 + 0 + 1 + 2) / 4)
    assert criteria.EI_empirical(samples, 10.0) == 0.0


def test_ei_gaussian_matches_monte_carlo():
    rng = np.random.default_rng(0)
    mean, var, thresh = 1.0, 4.0, 2.0
    samples = rng.normal(mean, np.sqrt(var), 2_000_000)
    mc = np.maximum(samples - thresh, 0).mean()
    assert criteria.EI_gaussian(mean, var, thresh) == pytest.approx(mc, rel=0.01)


def test_ei_gaussian_far_above_thresh():
    # when mean >> thresh, EI -> mean - thresh
    assert criteria.EI_gaussian(10.0, 0.01, 0.0) == pytest.approx(10.0, rel=1e-3)


def test_log_ei_consistent_with_ei():
    for mean, var, thresh in [(0.0, 1.0, 1.0), (2.0, 0.5, 1.0), (-1.0, 2.0, 3.0)]:
        assert criteria.logEI_gaussian(mean, var, thresh) == pytest.approx(
            np.log(criteria.EI_gaussian(mean, var, thresh)), rel=1e-6
        )


def test_log_ei_asymptotic_branch_continuous():
    # across the z = -34 switch the function must be finite and decreasing
    var = 1.0
    vals = [criteria.logEI_gaussian(0.0, var, t) for t in (33.0, 34.0, 35.0, 40.0)]
    assert all(np.isfinite(v) for v in vals)
    assert all(a > b for a, b in zip(vals, vals[1:]))


def test_ucb():
    assert criteria.UCB(1.0, 4.0, 2.0) == 5.0
    assert criteria.UCB(1.0, 4.0, 0.0) == 1.0
