"""Random-search tests (reference parity: hyperopt/tests/test_rand.py):
distributional sanity over benchmark domains + doc structure.
"""

import numpy as np
import pytest

from hyperopt_tpu import Domain, Trials, fmin
from hyperopt_tpu.algos import rand
from hyperopt_tpu.models import domains


def test_suggest_doc_structure():
    d = domains.get("branin")
    domain = Domain(d.fn, d.space)
    trials = Trials()
    ids = trials.new_trial_ids(5)
    docs = rand.suggest(ids, domain, trials, seed=42)
    assert len(docs) == 5
    for doc, tid in zip(docs, ids):
        assert doc["tid"] == tid
        assert doc["misc"]["idxs"]["x"] == [tid]
        assert isinstance(doc["misc"]["vals"]["x"][0], float)
        assert -5 <= doc["misc"]["vals"]["x"][0] <= 10


def test_suggest_deterministic_in_seed():
    d = domains.get("branin")
    domain = Domain(d.fn, d.space)
    trials = Trials()
    ids = [0, 1, 2]
    a = rand.suggest(ids, domain, trials, seed=7)
    b = rand.suggest(ids, domain, trials, seed=7)
    assert [x["misc"]["vals"] for x in a] == [x["misc"]["vals"] for x in b]
    c = rand.suggest(ids, domain, trials, seed=8)
    assert [x["misc"]["vals"] for x in a] != [x["misc"]["vals"] for x in c]


@pytest.mark.parametrize("name", ["quadratic1", "gauss_wave", "branin", "many_dists"])
def test_rand_quality_on_domains(name):
    d = domains.get(name)
    trials = Trials()
    fmin(
        d.fn,
        d.space,
        algo=rand.suggest,
        max_evals=d.quality_evals * 2,  # rand gets 2x budget vs guided algos
        trials=trials,
        rstate=np.random.default_rng(0),
        show_progressbar=False,
        verbose=False,
    )
    assert min(trials.losses()) < d.quality_threshold * (
        1 if d.quality_threshold < 0 else 1.5
    ) + (0.3 if name == "branin" else 0.0)


def test_rand_covers_space():
    d = domains.get("many_dists")
    domain = Domain(d.fn, d.space)
    trials = Trials()
    docs = rand.suggest(list(range(200)), domain, trials, seed=0)
    a_vals = [doc["misc"]["vals"]["a"][0] for doc in docs]
    assert set(a_vals) == {0, 1, 2}
    k_vals = [doc["misc"]["vals"]["k"][0] for doc in docs]
    assert np.mean(k_vals) > 0.75  # pchoice weights respected
