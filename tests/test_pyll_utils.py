"""Tests for the hp DSL + expr_to_config conditionality extraction.

Mirrors the reference's test_pyll_utils.py (SURVEY.md §4): expected
conditions per label; DuplicateLabel raises.
"""

import numpy as np
import pytest

from hyperopt_tpu import hp
from hyperopt_tpu.exceptions import DuplicateLabel
from hyperopt_tpu.pyll import sample, scope
from hyperopt_tpu.pyll_utils import EQ, Cond, expr_to_config


RNG = lambda s=0: np.random.default_rng(s)


def test_hp_uniform_structure():
    node = hp.uniform("x", -1.0, 1.0)
    assert node.name == "float"
    hparam = node.pos_args[0]
    assert hparam.name == "hyperopt_param"
    assert hparam.pos_args[0].obj == "x"
    assert hparam.pos_args[1].name == "uniform"


def test_hp_choice_structure():
    node = hp.choice("c", ["a", "b", "c"])
    assert node.name == "switch"
    idx = node.pos_args[0]
    assert idx.name == "hyperopt_param"
    assert idx.pos_args[1].name == "randint"


@pytest.mark.parametrize(
    "make,check",
    [
        (lambda: hp.uniform("x", 0, 1), lambda v: 0 <= v < 1),
        (lambda: hp.quniform("x", 0, 10, 2), lambda v: v % 2 == 0),
        (lambda: hp.uniformint("x", 0, 10), lambda v: isinstance(v, int)),
        (lambda: hp.loguniform("x", np.log(1e-3), np.log(1e3)), lambda v: 1e-3 <= v <= 1e3),
        (lambda: hp.qloguniform("x", np.log(1), np.log(100), 5), lambda v: v % 5 == 0),
        (lambda: hp.normal("x", 0, 1), lambda v: isinstance(v, float)),
        (lambda: hp.qnormal("x", 0, 5, 1), lambda v: v == round(v)),
        (lambda: hp.lognormal("x", 0, 1), lambda v: v > 0),
        (lambda: hp.qlognormal("x", 0, 1, 1), lambda v: v >= 0 and v == round(v)),
        (lambda: hp.randint("x", 5), lambda v: 0 <= v < 5),
        (lambda: hp.randint("x", 3, 8), lambda v: 3 <= v < 8),
        (lambda: hp.choice("x", [10, 20]), lambda v: v in (10, 20)),
        (lambda: hp.pchoice("x", [(0.3, "a"), (0.7, "b")]), lambda v: v in ("a", "b")),
    ],
)
def test_hp_dists_sample_in_support(make, check):
    node = make()
    for seed in range(20):
        assert check(sample(node, RNG(seed)))


def test_label_must_be_string():
    with pytest.raises(TypeError):
        hp.uniform(3, 0, 1)
    with pytest.raises(TypeError):
        hp.choice(None, [1, 2])


def test_pchoice_probs_must_sum_to_one():
    with pytest.raises(ValueError):
        hp.pchoice("p", [(0.5, "a"), (0.1, "b")])


def test_choice_rejects_dict():
    with pytest.raises(TypeError):
        hp.choice("c", {"a": 1})


def test_expr_to_config_flat():
    space = {"x": hp.uniform("x", 0, 1), "y": hp.randint("y", 4)}
    hps = {}
    expr_to_config(space, (), hps)
    assert set(hps) == {"x", "y"}
    assert hps["x"]["conditions"] == {()}
    assert hps["x"]["node"].name == "uniform"
    assert hps["y"]["node"].name == "randint"


def test_expr_to_config_conditional():
    space = hp.choice(
        "root",
        [
            {"kind": "svm", "C": hp.lognormal("C", 0, 1)},
            {"kind": "dtree", "depth": hp.randint("depth", 10)},
        ],
    )
    hps = {}
    expr_to_config(space, (), hps)
    assert set(hps) == {"root", "C", "depth"}
    assert hps["root"]["conditions"] == {()}
    assert hps["C"]["conditions"] == {(EQ("root", 0),)}
    assert hps["depth"]["conditions"] == {(EQ("root", 1),)}


def test_expr_to_config_nested_conditions():
    inner = hp.choice("inner", [hp.uniform("a", 0, 1), hp.uniform("b", 0, 1)])
    space = hp.choice("outer", [inner, {"c": hp.uniform("c", 0, 1)}])
    hps = {}
    expr_to_config(space, (), hps)
    assert hps["a"]["conditions"] == {(EQ("outer", 0), EQ("inner", 0))}
    assert hps["b"]["conditions"] == {(EQ("outer", 0), EQ("inner", 1))}
    assert hps["c"]["conditions"] == {(EQ("outer", 1),)}
    assert hps["inner"]["conditions"] == {(EQ("outer", 0),)}


def test_expr_to_config_shared_param_across_branches():
    shared = hp.uniform("lr", 0, 1)
    space = hp.choice("m", [{"lr": shared}, {"lr": shared, "extra": hp.uniform("e", 0, 1)}])
    hps = {}
    expr_to_config(space, (), hps)
    # same node under both branches -> two conjunctions, no DuplicateLabel
    assert hps["lr"]["conditions"] == {(EQ("m", 0),), (EQ("m", 1),)}


def test_duplicate_label_raises():
    space = {"a": hp.uniform("x", 0, 1), "b": hp.uniform("x", 0, 1)}
    hps = {}
    with pytest.raises(DuplicateLabel):
        expr_to_config(space, (), hps)


def test_unconditional_shadows_conditional():
    shared = hp.uniform("u", 0, 1)
    space = {"always": shared, "maybe": hp.choice("c", [shared, 0])}
    hps = {}
    expr_to_config(space, (), hps)
    assert hps["u"]["conditions"] == {()}


def test_cond_eval():
    c = EQ("x", 2)
    assert c({"x": 2})
    assert not c({"x": 3})
    assert not c({"x": None})
    with pytest.raises(KeyError):
        c({})
    assert Cond("y", 5, ">")({"y": 7})
    assert Cond("y", 5, "<")({"y": 3})


def test_conditional_sampling_end_to_end():
    space = hp.choice(
        "algo",
        [
            {"name": "sgd", "lr": hp.loguniform("lr", -5, 0)},
            {"name": "adam", "beta": hp.uniform("beta", 0.8, 1.0)},
        ],
    )
    seen = set()
    for seed in range(30):
        s = sample(space, RNG(seed))
        seen.add(s["name"])
        if s["name"] == "sgd":
            assert "lr" in s and "beta" not in s
        else:
            assert "beta" in s and "lr" not in s
    assert seen == {"sgd", "adam"}
