"""Prometheus exposition-format validator (ISSUE 9 satellite).

Every stats family used to be shape-tested in isolation; this is the
parser-level check over the FULL ``render_prometheus(...)`` output with
every section populated at once: HELP/TYPE pairing for every exposed
metric, valid sample lines, label-value escaping, no duplicate series,
histogram structure (monotone cumulative buckets, ``+Inf`` == count),
and counter naming.  A new stats family added without exposition
discipline fails here, not in a scrape.
"""

import math
import re

from hyperopt_tpu.control import ControlStats
from hyperopt_tpu.observability import (
    DeviceStats,
    FaultStats,
    PhaseTimings,
    ServiceStats,
    SpeculationStats,
    StoreStats,
    build_info,
    render_prometheus,
)

_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_LABEL_RE = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")
# one sample line: name{labels} value  (labels optional)
_SAMPLE_RE = re.compile(
    r"^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)"
    r"(?:\{(?P<labels>.*)\})?"
    r" (?P<value>\S+)$"
)
# one label pair inside {...}: key="escaped value"
_LABEL_PAIR_RE = re.compile(
    r'(?P<key>[a-zA-Z_][a-zA-Z0-9_]*)="(?P<value>(?:[^"\\]|\\.)*)"'
)


def _full_exposition():
    """Every render section populated, including the awkward values:
    label characters needing escaping, None (NaN) gauges, +Inf
    histogram edges, multi-label series."""
    timings = PhaseTimings()
    timings.record("suggest", 0.5)
    spec = SpeculationStats()
    spec.record_dispatch(0.1)
    spec.record_sync(0.2)
    faults = FaultStats()
    faults.record("lease_expired")
    faults.record('chaos_torn_doc"quoted\\path')  # escaping exercise
    faults.record_backoff(0.7)
    service = ServiceStats()
    service.record_request("suggest", seconds=0.02, study='s"tricky\\1')
    service.record_request("suggest", seconds=7.0, study="s2", cold=True)
    service.record_rejection("suggest")
    service.record_error("report")
    service.record_replay("suggest")
    service.record_dispatch(4, 0.1)
    service.record_phase("dispatch", 0.08)
    service.record_compile(1024, "cont+idx")
    service.record_inline(2)
    service.set_queue_depth(3)
    service.set_n_studies(2)
    device = DeviceStats()
    device.record_dispatch({
        "sig": "h1024/cont", "device_s": 0.01, "n_requests": 4,
        "binding_ceiling": "hbm_bw", "roofline_pct": 12.5,
        "hbm_bytes": 1e6, "flops": 2e6, "live_bytes": 4096,
        "compiled": False,
    })
    device.set_backend_peak_bytes(1 << 20)
    store = StoreStats()
    store.record_fsync(0.001, kind="doc", nbytes=512)
    store.record_fsync(3.0, kind="journal", nbytes=128)  # +Inf bucket
    store.record_doc_write(512)
    store.record_attachment_write(64)
    store.record_scan(10)
    store.record_refresh(local=True)
    store.record_refresh(local=False)
    store.record_journal_append(128)
    store.record_journal_compaction(1000)
    store.record_journal_torn(1)
    store.record_lease("grant")
    store.record_quarantine(1)
    study_health = {
        "rows": [{
            "study": 'zoo"1\\x', "best_loss": 0.5, "regret": None,
            "gamma": 0.25, "n_below": 4, "ei_max": 1.5,
            "ei_flatness": 0.3, "state": "OK",
        }],
        "truncated_total": 7,
    }
    slo_rows = [
        {"rule": "SL601", "status": "ok", "burn_fast": 0.1,
         "burn_slow": 0.05, "breaches_total": 0},
        {"rule": "SL605", "status": "breach", "burn_fast": 2.0,
         "burn_slow": None, "breaches_total": 3},
    ]
    control = ControlStats()
    for outcome in ("proposed", "applied", "evaluated", "discarded",
                    "reverted"):
        control.record_decision(outcome)
    control.set_objective(0.125)
    control.set_frozen(True)
    control.record_reclaimed()
    control.record_resumed()
    return render_prometheus(
        timings=timings, speculation=spec, faults=faults,
        service=service, device=device, study_health=study_health,
        store=store, slo=slo_rows,
        control=control.control_metrics(), build=build_info(),
        extra={"service_uptime_seconds": 12.5},
    )


def parse_exposition(text):
    """Parse the exposition; raises AssertionError on any structural
    violation.  Returns {metric_name: {"help", "type", "samples"}}
    where samples is a list of (label_tuple, value)."""
    families = {}
    pending_help = {}
    last_decl = None
    assert text.endswith("\n"), "exposition must end with a newline"
    for lineno, line in enumerate(text.splitlines(), 1):
        assert line.strip() == line, f"line {lineno}: stray whitespace"
        assert line, f"line {lineno}: blank line"
        if line.startswith("# HELP "):
            _, _, rest = line.partition("# HELP ")
            name, _, help_text = rest.partition(" ")
            assert _NAME_RE.match(name), f"line {lineno}: bad name {name}"
            assert help_text, f"line {lineno}: empty HELP"
            pending_help[name] = help_text
            last_decl = ("help", name)
        elif line.startswith("# TYPE "):
            _, _, rest = line.partition("# TYPE ")
            name, _, kind = rest.partition(" ")
            assert kind in ("counter", "gauge", "histogram", "summary"), (
                f"line {lineno}: bad TYPE {kind!r}"
            )
            # HELP must immediately precede TYPE for the same metric
            assert last_decl == ("help", name), (
                f"line {lineno}: TYPE {name} without preceding HELP"
            )
            assert name not in families, (
                f"line {lineno}: duplicate TYPE declaration for {name}"
            )
            families[name] = {
                "help": pending_help[name], "type": kind, "samples": [],
            }
            last_decl = ("type", name)
        else:
            m = _SAMPLE_RE.match(line)
            assert m, f"line {lineno}: unparseable sample {line!r}"
            name = m.group("name")
            # histogram samples attach to their declared family
            base = name
            for suffix in ("_bucket", "_sum", "_count"):
                if name.endswith(suffix) and name[: -len(suffix)] in families:
                    base = name[: -len(suffix)]
            assert base in families, (
                f"line {lineno}: sample {name} without HELP/TYPE"
            )
            labels = []
            raw = m.group("labels")
            if raw is not None:
                consumed = 0
                for pm in _LABEL_PAIR_RE.finditer(raw):
                    key = pm.group("key")
                    assert _LABEL_RE.match(key)
                    labels.append((key, pm.group("value")))
                    consumed += pm.end() - pm.start()
                # everything between pairs must be separators (commas)
                leftovers = _LABEL_PAIR_RE.sub("", raw).replace(",", "")
                assert not leftovers, (
                    f"line {lineno}: malformed labels {raw!r}"
                )
            value = m.group("value")
            if value not in ("NaN", "+Inf", "-Inf"):
                float(value)  # must parse
            families[base]["samples"].append(
                (name, tuple(sorted(labels)), value)
            )
            last_decl = None
    return families


class TestExpositionFormat:
    def test_full_render_parses_with_no_duplicates(self):
        text = _full_exposition()
        families = parse_exposition(text)
        # every family present once, with at least one sample
        assert len(families) > 30
        seen_series = set()
        for fam, rec in families.items():
            assert rec["samples"], f"{fam} declared but no samples"
            for name, labels, _value in rec["samples"]:
                key = (name, labels)
                assert key not in seen_series, f"duplicate series {key}"
                seen_series.add(key)

    def test_every_stats_family_is_exposed(self):
        families = parse_exposition(_full_exposition())
        expected = {
            # driver / speculation / faults
            "hyperopt_phase_seconds_total",
            "hyperopt_speculation_seconds_total",
            "hyperopt_fault_events_total",
            "hyperopt_fault_backoff_seconds_total",
            # service
            "hyperopt_service_requests_total",
            "hyperopt_service_rejected_total",
            "hyperopt_service_errors_total",
            "hyperopt_service_idempotent_replays_total",
            "hyperopt_service_suggest_duration_seconds",
            "hyperopt_service_suggest_split_latency_ms",
            "hyperopt_service_suggest_split_total",
            "hyperopt_compile_events_total",
            "hyperopt_service_batch_occupancy",
            # device
            "hyperopt_device_duty_cycle",
            "hyperopt_device_roofline_pct",
            "hyperopt_device_memory_highwater_bytes",
            # study health
            "hyperopt_study_best_loss",
            "hyperopt_study_health",
            "hyperopt_studies_truncated_total",
            # store (new)
            "hyperopt_store_fsyncs_total",
            "hyperopt_store_fsync_duration_seconds",
            "hyperopt_store_doc_writes_total",
            "hyperopt_store_scans_total",
            "hyperopt_store_refresh_total",
            "hyperopt_store_journal_appends_total",
            "hyperopt_store_journal_torn_lines_total",
            # segmented trial log (new)
            "hyperopt_store_segment_appends_total",
            "hyperopt_store_segment_records_total",
            "hyperopt_store_segment_seals_total",
            "hyperopt_store_segment_compactions_total",
            "hyperopt_store_segment_replays_total",
            "hyperopt_store_segment_replay_records_total",
            "hyperopt_store_segment_torn_lines_total",
            "hyperopt_store_segments_pulled_total",
            "hyperopt_store_lease_events_total",
            "hyperopt_store_quarantined_docs_total",
            # slo (new)
            "hyperopt_slo_status",
            "hyperopt_slo_burn_rate",
            "hyperopt_slo_breaches_total",
            # control plane (new)
            "hyperopt_control_decisions_total",
            "hyperopt_control_objective",
            "hyperopt_control_frozen",
            "hyperopt_control_freezes_total",
            "hyperopt_control_reclaimed_studies_total",
            "hyperopt_control_resumed_studies_total",
            # identity (new)
            "hyperopt_build_info",
        }
        missing = expected - set(families)
        assert not missing, f"families missing from exposition: {missing}"

    def test_counter_names_end_in_total(self):
        families = parse_exposition(_full_exposition())
        for fam, rec in families.items():
            if rec["type"] == "counter":
                assert fam.endswith("_total"), (
                    f"counter {fam} must end in _total"
                )

    def test_histograms_are_monotone_and_closed(self):
        families = parse_exposition(_full_exposition())
        hists = [
            fam for fam, rec in families.items()
            if rec["type"] == "histogram"
        ]
        assert "hyperopt_service_suggest_duration_seconds" in hists
        assert "hyperopt_store_fsync_duration_seconds" in hists
        for fam in hists:
            rec = families[fam]
            buckets = [
                (dict(labels)["le"], float(value))
                for name, labels, value in rec["samples"]
                if name == f"{fam}_bucket"
            ]
            count = [
                float(value) for name, _, value in rec["samples"]
                if name == f"{fam}_count"
            ]
            assert buckets and count
            # cumulative counts monotone nondecreasing, +Inf last and
            # equal to _count
            values = [v for _, v in buckets]
            assert values == sorted(values), (fam, values)
            assert buckets[-1][0] == "+Inf"
            assert buckets[-1][1] == count[0]
            edges = [
                float(le) for le, _ in buckets[:-1]
            ]
            assert edges == sorted(edges)

    def test_label_escaping_round_trips(self):
        text = _full_exposition()
        families = parse_exposition(text)
        studies = {
            dict(labels).get("study")
            for _, labels, _ in families[
                "hyperopt_service_study_suggests_total"
            ]["samples"]
        }
        # the escaped form is on the wire; unescaping recovers the
        # original tricky id
        tricky = next(s for s in studies if "tricky" in s)
        unescaped = (
            tricky.replace("\\\\", "\0")
            .replace('\\"', '"')
            .replace("\\n", "\n")
            .replace("\0", "\\")
        )
        assert unescaped == 's"tricky\\1'

    def test_build_info_identity_gauge(self):
        families = parse_exposition(_full_exposition())
        ((name, labels, value),) = families["hyperopt_build_info"][
            "samples"
        ]
        keys = dict(labels)
        assert set(keys) == {"version", "jax", "backend"}
        assert float(value) == 1.0

    def test_control_families_populated(self):
        families = parse_exposition(_full_exposition())
        outcomes = {
            dict(labels)["outcome"]
            for _, labels, _ in families[
                "hyperopt_control_decisions_total"
            ]["samples"]
        }
        assert {"applied", "evaluated", "reverted"} <= outcomes
        ((_, _, frozen),) = families["hyperopt_control_frozen"][
            "samples"
        ]
        assert float(frozen) == 1.0
        ((_, _, obj),) = families["hyperopt_control_objective"][
            "samples"
        ]
        assert float(obj) == 0.125
        ((_, _, reclaimed),) = families[
            "hyperopt_control_reclaimed_studies_total"
        ]["samples"]
        assert float(reclaimed) == 1.0
        ((_, _, resumed),) = families[
            "hyperopt_control_resumed_studies_total"
        ]["samples"]
        assert float(resumed) == 1.0

    def test_nan_renders_as_NaN_token(self):
        families = parse_exposition(_full_exposition())
        # SL605's burn_slow was None → NaN sample token, not 'None'
        burns = {
            (dict(labels)["rule"], dict(labels)["window"]): value
            for _, labels, value in families["hyperopt_slo_burn_rate"][
                "samples"
            ]
        }
        assert burns[("SL605", "slow")] == "NaN"
        assert not math.isnan(float(burns[("SL601", "fast")]))
