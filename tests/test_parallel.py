"""Parallel backend tests on the virtual 8-device CPU mesh (reference
parity: hyperopt/tests/test_spark.py's local[*] pattern — real coordination
substrate, in-process workers).
"""

import os
import threading
import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from hyperopt_tpu import Trials, fmin, hp
from hyperopt_tpu.algos import rand, tpe
from hyperopt_tpu.base import JOB_STATE_CANCEL, JOB_STATE_DONE
from hyperopt_tpu.models import domains
from hyperopt_tpu.parallel.jax_trials import JaxTrials
from hyperopt_tpu.parallel.sharding import (
    default_mesh,
    make_sharded_batch_eval,
    make_sharded_score,
    pad_mixture,
)


class TestSharding:
    def test_default_mesh_shapes(self):
        mesh = default_mesh()
        assert mesh.shape["dp"] * mesh.shape["sp"] == len(jax.devices())

    def test_sharded_score_matches_reference_kernel(self):
        """shard_map blockwise logsumexp == single-device gmm_lpdf."""
        from hyperopt_tpu.ops.gmm import gmm_lpdf

        mesh = default_mesh()
        dp, sp = int(mesh.shape["dp"]), int(mesh.shape["sp"])
        rng = np.random.default_rng(0)
        K = sp * 6
        C = dp * 10
        wb = rng.uniform(0.1, 1.0, K).astype(np.float32)
        wb[-2:] = 0.0  # padded tail
        wb /= wb.sum()
        mb = rng.normal(0, 2, K).astype(np.float32)
        sb = rng.uniform(0.5, 2.0, K).astype(np.float32)
        wa = rng.uniform(0.1, 1.0, K).astype(np.float32)
        wa /= wa.sum()
        ma = rng.normal(0, 2, K).astype(np.float32)
        sa = rng.uniform(0.5, 2.0, K).astype(np.float32)
        cand = rng.uniform(-4, 4, C).astype(np.float32)
        lo, hi = np.float32(-5.0), np.float32(5.0)

        sharded = np.asarray(
            make_sharded_score(mesh)(cand, wb, mb, sb, wa, ma, sa, lo, hi)
        )
        ref = np.asarray(
            gmm_lpdf(cand, wb, mb, sb, lo, hi, np.float32(0.0), False, False)
        ) - np.asarray(
            gmm_lpdf(cand, wa, ma, sa, lo, hi, np.float32(0.0), False, False)
        )
        np.testing.assert_allclose(sharded, ref, rtol=2e-4, atol=2e-4)

    def test_sharded_batch_eval(self):
        mesh = default_mesh()
        run = make_sharded_batch_eval(mesh, lambda c: c["x"] ** 2 + c["y"])
        B = int(mesh.shape["dp"]) * 3
        batch = {
            "x": np.arange(B, dtype=np.float32),
            "y": np.ones(B, dtype=np.float32),
        }
        out = np.asarray(run(batch))
        np.testing.assert_allclose(out, batch["x"] ** 2 + 1.0, rtol=1e-6)

    def test_pad_mixture(self):
        w, m, s = pad_mixture(
            np.ones(3, np.float32), np.arange(3, dtype=np.float32), np.ones(3, np.float32), 8
        )
        assert w.shape == (8,)
        assert w[3:].sum() == 0.0


class TestJaxTrials:
    def test_parallel_fmin_runs_all_trials(self):
        d = domains.get("quadratic1")
        trials = JaxTrials(parallelism=4)
        best = fmin(
            d.fn, d.space, algo=rand.suggest, max_evals=20, trials=trials,
            rstate=np.random.default_rng(0), show_progressbar=False, verbose=False,
        )
        assert len(trials) == 20
        assert all(t["state"] == JOB_STATE_DONE for t in trials.trials)
        assert "x" in best

    def test_trials_actually_run_concurrently(self):
        active = {"now": 0, "max": 0}
        lock = threading.Lock()

        def slow(c):
            with lock:
                active["now"] += 1
                active["max"] = max(active["max"], active["now"])
            time.sleep(0.15)
            with lock:
                active["now"] -= 1
            return (c["x"] - 3) ** 2

        trials = JaxTrials(parallelism=4)
        fmin(
            slow, {"x": hp.uniform("x", -5, 5)}, algo=rand.suggest,
            max_evals=8, trials=trials,
            rstate=np.random.default_rng(0), show_progressbar=False, verbose=False,
        )
        assert active["max"] >= 2, f"no concurrency observed: {active}"

    def test_trial_timeout_cancels(self):
        def sometimes_hangs(c):
            if c["x"] > 0:
                time.sleep(5.0)
            return abs(c["x"])

        trials = JaxTrials(parallelism=4, trial_timeout=0.3)
        fmin(
            sometimes_hangs, {"x": hp.uniform("x", -5, 5)}, algo=rand.suggest,
            max_evals=6, trials=trials, timeout=10,
            rstate=np.random.default_rng(0), show_progressbar=False, verbose=False,
            return_argmin=False,
        )
        states = [t["state"] for t in trials._dynamic_trials]
        assert JOB_STATE_CANCEL in states
        assert JOB_STATE_DONE in states

    def test_objective_error_recorded(self):
        def sometimes_fails(c):
            if c["x"] < 0:
                raise RuntimeError("neg")
            return c["x"]

        trials = JaxTrials(parallelism=2)
        fmin(
            sometimes_fails, {"x": hp.uniform("x", -5, 5)}, algo=rand.suggest,
            max_evals=8, trials=trials, catch_eval_exceptions=True,
            rstate=np.random.default_rng(0), show_progressbar=False, verbose=False,
            return_argmin=False,
        )
        from hyperopt_tpu.base import JOB_STATE_ERROR

        errs = [t for t in trials._dynamic_trials if t["state"] == JOB_STATE_ERROR]
        assert errs and all("neg" in t["misc"]["error"][1] for t in errs)

    def test_device_plane_vectorized_eval(self):
        def branin_jax(cfg):
            x, y = cfg["x"], cfg["y"]
            a, b, c = 1.0, 5.1 / (4 * jnp.pi ** 2), 5.0 / jnp.pi
            r, s, t = 6.0, 10.0, 1.0 / (8 * jnp.pi)
            return (
                a * (y - b * x ** 2 + c * x - r) ** 2 + s * (1 - t) * jnp.cos(x) + s
            )

        d = domains.get("branin")
        trials = JaxTrials(parallelism=8, device_fn=branin_jax)
        fmin(
            d.fn, d.space, algo=rand.suggest, max_evals=24, trials=trials,
            rstate=np.random.default_rng(0), show_progressbar=False, verbose=False,
            return_argmin=False,
        )
        assert len(trials) == 24
        # device losses match the host objective
        for t in trials.trials:
            cfg = {k: v[0] for k, v in t["misc"]["vals"].items()}
            assert t["result"]["loss"] == pytest.approx(d.fn(cfg), rel=1e-3)

    def test_tpe_with_parallel_backend(self):
        d = domains.get("quadratic1")
        trials = JaxTrials(parallelism=4)
        fmin(
            d.fn, d.space, algo=tpe.suggest, max_evals=40, trials=trials,
            rstate=np.random.default_rng(0), show_progressbar=False, verbose=False,
            return_argmin=False,
        )
        assert len(trials) == 40
        assert min(trials.losses()) < 0.5


class TestGraftEntry:
    def test_entry_compiles(self):
        import __graft_entry__ as g

        fn, args = g.entry()
        out = np.asarray(jax.jit(fn)(*args))
        assert out.shape == (1,)
        assert np.isfinite(out).all()

    def test_dryrun_multichip(self, capsys):
        import __graft_entry__ as g

        g.dryrun_multichip(8)
        assert "dryrun_multichip ok" in capsys.readouterr().out


class TestShardedSuggest:
    def test_tpe_suggest_with_mesh(self):
        """tpe.suggest(mesh=...) shards scoring and still yields valid,
        quality-comparable suggestions."""
        from functools import partial

        from hyperopt_tpu.parallel.sharding import default_mesh

        d = domains.get("branin")
        trials = Trials()
        fmin(
            d.fn, d.space, algo=rand.suggest, max_evals=30, trials=trials,
            rstate=np.random.default_rng(0), show_progressbar=False, verbose=False,
        )
        from hyperopt_tpu import Domain

        mesh = default_mesh()
        domain = Domain(d.fn, d.space)
        docs = tpe.suggest([100, 101], domain, trials, seed=4, mesh=mesh)
        assert len(docs) == 2
        for doc in docs:
            assert -5.0 <= doc["misc"]["vals"]["x"][0] <= 10.0
            assert 0.0 <= doc["misc"]["vals"]["y"][0] <= 15.0

    def test_sharded_fmin_quality(self):
        from functools import partial

        from hyperopt_tpu.parallel.sharding import default_mesh

        d = domains.get("quadratic1")
        algo = partial(tpe.suggest, mesh=default_mesh(), n_startup_jobs=10)
        trials = Trials()
        fmin(
            d.fn, d.space, algo=algo, max_evals=40, trials=trials,
            rstate=np.random.default_rng(0), show_progressbar=False, verbose=False,
        )
        assert len(trials) == 40
        assert min(trials.losses()) < 0.5

    def test_sharded_best_matches_host_argmax(self):
        """make_sharded_best (device-side argmax + winner gather) agrees
        with host argmax over make_sharded_score's output — the O(k)-
        readback rewrite must not change which candidate wins."""
        import jax.numpy as jnp

        from hyperopt_tpu.parallel.sharding import (
            default_mesh,
            make_sharded_best,
            make_sharded_score,
        )

        mesh = default_mesh()
        dp, sp = int(mesh.shape["dp"]), int(mesh.shape["sp"])
        rng = np.random.default_rng(0)
        k, n_cand = 2, 64 * dp // 2
        C = k * n_cand
        K = 4 * sp
        cand = rng.uniform(-3, 3, C).astype(np.float32)

        def mk():
            w = rng.uniform(0.1, 1, K).astype(np.float32)
            w /= w.sum()
            return (
                w,
                rng.normal(0, 1, K).astype(np.float32),
                rng.uniform(0.3, 1.5, K).astype(np.float32),
            )

        wb, mb, sb = mk()
        wa, ma, sa = mk()
        lo, hi = np.float32(-10.0), np.float32(10.0)

        host_scores = np.asarray(
            make_sharded_score(mesh)(cand, wb, mb, sb, wa, ma, sa, lo, hi)
        ).reshape(k, n_cand)
        host_best = cand.reshape(k, n_cand)[
            np.arange(k), np.argmax(host_scores, axis=1)
        ]
        dev_best = np.asarray(
            make_sharded_best(mesh)(
                jnp.asarray(cand), jnp.asarray(cand), wb, mb, sb, wa, ma, sa,
                lo, hi, k=k, n_cand=n_cand,
            )
        )
        np.testing.assert_allclose(dev_best, host_best, rtol=1e-6)

    def test_mesh_respects_param_locks(self):
        """Host/mesh parity of the lock cascade: a soft lock confines the
        sharded path's suggestion exactly like the host path's."""
        from hyperopt_tpu import Domain
        from hyperopt_tpu.parallel.sharding import default_mesh

        d = domains.get("quadratic1")
        trials = Trials()
        fmin(
            d.fn, d.space, algo=rand.suggest, max_evals=30, trials=trials,
            rstate=np.random.default_rng(1), show_progressbar=False, verbose=False,
        )
        domain = Domain(d.fn, d.space)
        locks = {"x": (2.0, 0.5)}
        for mesh in (None, default_mesh()):
            docs = tpe.suggest(
                [200], domain, trials, seed=7, param_locks=locks, mesh=mesh
            )
            x = docs[0]["misc"]["vals"]["x"][0]
            assert 1.5 - 1e-6 <= x <= 2.5 + 1e-6, (mesh, x)

    def test_mesh_respects_trial_filter(self):
        """Host/mesh parity of observation filtering: a filter that
        removes every trial with x<0 must confine BOTH paths' below-set
        evidence; verify the mesh path accepts the same mask and yields
        an in-support suggestion differing from the unfiltered one."""
        from hyperopt_tpu import Domain
        from hyperopt_tpu.parallel.sharding import default_mesh

        d = domains.get("quadratic1")
        trials = Trials()
        fmin(
            d.fn, d.space, algo=rand.suggest, max_evals=40, trials=trials,
            rstate=np.random.default_rng(2), show_progressbar=False, verbose=False,
        )
        domain = Domain(d.fn, d.space)
        hist = trials.history
        xv = {t: v for t, v in zip(hist.idxs["x"], hist.vals["x"])}
        mask = np.array([xv[t] >= 0 for t in hist.loss_tids], dtype=bool)

        for mesh in (None, default_mesh()):
            a = tpe.suggest([300], domain, trials, seed=9, trial_filter=mask,
                            mesh=mesh)
            b = tpe.suggest([300], domain, trials, seed=9, mesh=mesh)
            assert a[0]["misc"]["vals"] != b[0]["misc"]["vals"], mesh
            assert -5.0 <= a[0]["misc"]["vals"]["x"][0] <= 5.0

    def test_mesh_quantized_labels_shard(self):
        """Quantized dists shard through the CDF-bucket scorer (no more
        single-device fallthrough): mesh suggestions stay on the q-grid,
        in bounds, and the sharded scorer agrees with the exact
        single-device quantized lpdf."""
        import jax.numpy as jnp

        from hyperopt_tpu import Domain, hp
        from hyperopt_tpu.ops import gmm as gmm_ops
        from hyperopt_tpu.parallel.sharding import (
            default_mesh,
            make_sharded_quantized_score,
            pad_mixture,
        )

        space = {"w": hp.quniform("w", 0, 100, 5)}
        trials = Trials()
        fmin(
            lambda c: abs(c["w"] - 40) / 20, space, algo=rand.suggest,
            max_evals=25, trials=trials, rstate=np.random.default_rng(3),
            show_progressbar=False, verbose=False,
        )
        domain = Domain(lambda c: abs(c["w"] - 40) / 20, space)
        mesh = default_mesh()
        docs = tpe.suggest([400, 401], domain, trials, seed=11, mesh=mesh)
        for doc in docs:
            w = doc["misc"]["vals"]["w"][0]
            assert 0.0 <= w <= 100.0
            assert w % 5 == 0  # on the quantization grid

        # numeric parity of the sharded quantized scorer vs gmm_lpdf
        sp = int(mesh.shape["sp"])
        dp = int(mesh.shape["dp"])
        rng = np.random.default_rng(0)
        K = 4 * sp
        w_, mu, sg = (rng.uniform(0.1, 1, K).astype(np.float32),
                      rng.uniform(0, 100, K).astype(np.float32),
                      rng.uniform(1, 10, K).astype(np.float32))
        w_ /= w_.sum()
        wb, mb, sb = pad_mixture(w_, mu, sg, K)
        x = (np.round(rng.uniform(0, 100, 8 * dp) / 5) * 5).astype(np.float32)
        lo, hi, q = np.float32(0.0), np.float32(100.0), np.float32(5.0)
        sharded = np.asarray(
            make_sharded_quantized_score(mesh, log_scale=False)(
                x, wb, mb, sb, wb, mb, sb, lo, hi, q
            )
        )
        # l == g mixture -> score exactly 0; also check one-sided value
        np.testing.assert_allclose(sharded, 0.0, atol=1e-5)
        exact = np.asarray(
            gmm_ops.gmm_lpdf(x, wb, mb, sb, lo, hi, q, False, True)
        )
        one_sided = np.asarray(
            make_sharded_quantized_score(mesh, log_scale=False)(
                x, wb, mb, sb,
                np.ones(K, np.float32) / K, mb, sb, lo, hi, q,
            )
        )
        ga = np.asarray(
            gmm_ops.gmm_lpdf(
                x, np.ones(K, np.float32) / K, mb, sb, lo, hi, q, False, True
            )
        )
        np.testing.assert_allclose(one_sided, exact - ga, atol=1e-4)


class TestMultiProcessDistributed:
    """True multi-process jax.distributed: 2 interpreters × 2 virtual CPU
    devices form the (2, 2) dp×sp global mesh and run the production
    sharded scorer as one SPMD program — collectives cross the process
    boundary (Gloo, the CPU stand-in for DCN). The reference's analog is
    driver↔mongod↔worker over the network (SURVEY §3.4)."""

    def _run_pair(self, port):
        import subprocess
        import sys

        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        helper = os.path.join(repo, "tests", "distributed_score_helper.py")
        env = dict(os.environ)
        env["PYTHONPATH"] = os.pathsep.join(
            [repo] + ([env["PYTHONPATH"]] if env.get("PYTHONPATH") else [])
        )
        # the helper sets its own JAX_PLATFORMS/XLA_FLAGS before importing
        # jax; scrub the suite's 8-device flag so it doesn't double up
        env.pop("XLA_FLAGS", None)
        procs = [
            subprocess.Popen(
                [sys.executable, helper, str(i), str(port)],
                env=env, cwd=repo,
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            )
            for i in range(2)
        ]
        outs = {}
        try:
            for i, p in enumerate(procs):
                out, _ = p.communicate(timeout=240)
                outs[i] = out
        except subprocess.TimeoutExpired:
            # kill BOTH, then reap the ones not yet communicated (keeping
            # the finished process's output for the diagnostic) so no
            # zombies/pipe fds outlive the test; signal via timed_out
            for p in procs:
                p.kill()
            for i, p in enumerate(procs):
                if i not in outs:
                    out, _ = p.communicate()
                    outs[i] = out
            return procs, [outs[i] for i in range(len(procs))], True
        return procs, [outs[i] for i in range(len(procs))], False

    def test_sharded_score_across_two_processes(self, tmp_path):
        import socket

        last = None
        for _ in range(2):  # retry once: free-port discovery is racy,
            # whether the collision surfaces as a fast bind failure or as
            # a hang (a foreign listener accepting the coordinator dial)
            with socket.socket() as s:
                s.bind(("localhost", 0))
                port = s.getsockname()[1]
            procs, outs, timed_out = self._run_pair(port)
            last = (procs, outs, timed_out)
            if not timed_out and all(p.returncode == 0 for p in procs):
                break
        procs, outs, timed_out = last
        # Root cause of the long-standing failure in this container
        # (triaged for ISSUE 11): the bundled jaxlib's CPU backend has
        # no cross-process collective support — the helper's sharded
        # scorer dies with XlaRuntimeError "Multiprocess computations
        # aren't implemented on the CPU backend."  jax.distributed
        # initializes fine (the coordination service is pure gRPC); it
        # is the Gloo/XLA collective layer that is absent from this
        # jaxlib build.  Nothing in-repo can fix that (no new deps in
        # the image), so the capability is probed and the test skips —
        # it guards jax's multi-process substrate, not our code, which
        # the single-process 8-device mesh suite covers fully.
        if any(
            "Multiprocess computations aren't implemented" in out
            for out in outs
        ):
            pytest.skip(
                "jaxlib CPU backend lacks cross-process collectives "
                "in this environment"
            )
        assert not timed_out, "distributed helpers hung twice:\n" + "\n".join(outs)
        for i, (p, out) in enumerate(zip(procs, outs)):
            assert p.returncode == 0, f"proc {i} rc={p.returncode}:\n{out}"
            assert f"DIST_SCORE_OK pid={i}" in out, out


class TestLargeHistorySharding:
    """The long-context claim: full history sharded over the mesh instead
    of the reference's linear_forgetting truncation. Pins (a) sharded-
    scorer exactness against the single-device density at a 50k-component
    mixture and (b) a full tpe.suggest(mesh=…) over a 20k-trial history."""

    def test_sharded_score_parity_at_50k_components(self):
        import jax.numpy as jnp

        from hyperopt_tpu.ops.gmm import gmm_lpdf
        from hyperopt_tpu.parallel.sharding import (
            default_mesh,
            make_sharded_score,
            pad_mixture,
        )

        mesh = default_mesh()
        sp = int(mesh.shape["sp"])
        rng = np.random.default_rng(0)
        K, C = 50_000, 1024

        def mk(k):
            w = (np.abs(rng.normal(size=k)) + 0.1).astype(np.float32)
            return (w / w.sum(),
                    rng.normal(size=k).astype(np.float32),
                    (np.abs(rng.normal(size=k)) + 0.2).astype(np.float32))

        below, above = mk(64), mk(K)
        cand = rng.uniform(-3, 3, C).astype(np.float32)
        low, high = np.float32(-6.0), np.float32(6.0)
        # component axis padded up to an sp-divisible length (weight 0)
        pad = lambda k: -(-k // sp) * sp
        wb, mb, sb = pad_mixture(*below, pad(64))
        wa, ma, sa = pad_mixture(*above, pad(K))
        out = np.asarray(
            make_sharded_score(mesh)(
                jnp.asarray(cand), wb, mb, sb, wa, ma, sa,
                jnp.float32(low), jnp.float32(high),
            )
        )
        ref = np.asarray(
            gmm_lpdf(cand, *below, low, high, 0.0, False, False)
        ) - np.asarray(gmm_lpdf(cand, *above, low, high, 0.0, False, False))
        np.testing.assert_allclose(out, ref, atol=2e-3)

    def test_mesh_suggest_on_20k_history(self):
        from hyperopt_tpu import Domain, hp
        from hyperopt_tpu.base import JOB_STATE_DONE, STATUS_OK
        from hyperopt_tpu.parallel.sharding import default_mesh

        N = 20_000
        space = {"x": hp.uniform("x", -5, 5)}
        domain = Domain(lambda c: c["x"] ** 2, space)
        rng = np.random.default_rng(1)
        xs = rng.uniform(-5, 5, N)
        docs = [
            {
                "tid": i,
                "spec": None,
                "result": {"status": STATUS_OK, "loss": float(xs[i] ** 2)},
                "misc": {"tid": i, "cmd": None,
                         "idxs": {"x": [i]}, "vals": {"x": [float(xs[i])]}},
                "state": JOB_STATE_DONE,
                "owner": None,
                "book_time": None,
                "refresh_time": None,
                "exp_key": None,
            }
            for i in range(N)
        ]
        trials = Trials()
        trials._insert_trial_docs(docs)
        trials.refresh()
        docs = tpe.suggest(
            [N + 1], domain, trials, seed=5, mesh=default_mesh(),
            n_EI_candidates=2048,
        )
        x = docs[0]["misc"]["vals"]["x"][0]
        assert -5.0 <= x <= 5.0
        # 20k sharp quadratic observations: the posterior concentrates
        # hard around the optimum
        assert abs(x) < 1.0, x


class TestUnifiedMeshPath:
    """VERDICT r4 #2: tpe.suggest(mesh=...) rides the device-resident
    history + fused multi-family programs, with scoring sharded."""

    @pytest.mark.parametrize(
        "kb,ka",
        [
            (13, 41),   # boundary inside a shard
            (1, 70),    # minimal below region
            (33, 3),    # below spans shards, tiny above
        ],
    )
    def test_sharded_pair_score_batched_parity(self, kb, ka):
        """The batched sharded pair scorer == single-device pair_score,
        with the below/above boundary straddling shard boundaries."""
        from hyperopt_tpu.ops.score import NEG_BIG, pair_params, pair_score
        from hyperopt_tpu.parallel.sharding import (
            make_sharded_pair_score_batched,
        )

        mesh = default_mesh()
        dp, sp = int(mesh.shape["dp"]), int(mesh.shape["sp"])
        rng = np.random.default_rng(0)
        L, C = 3, 64 * dp

        def mk(k):
            w = (np.abs(rng.normal(size=(L, k))) + 0.1).astype(np.float32)
            return (
                w / w.sum(axis=1, keepdims=True),
                rng.normal(size=(L, k)).astype(np.float32),
                (np.abs(rng.normal(size=(L, k))) + 0.3).astype(np.float32),
            )

        B, A = mk(kb), mk(ka)
        z = rng.uniform(-3, 3, (L, C)).astype(np.float32)
        params = jax.vmap(pair_params)(*B, *A)  # [L, 3, kb+ka]
        ref = np.stack([
            np.asarray(pair_score(jnp.asarray(z[i]), params[i], kb))
            for i in range(L)
        ])
        # pad K to an sp multiple with NEG_BIG logit columns (zero mass)
        K = kb + ka
        k_pad = (-K) % sp
        pad_cols = jnp.zeros((L, 3, k_pad), params.dtype).at[:, 2, :].set(NEG_BIG)
        pp = jnp.concatenate([params, pad_cols], axis=2)
        got = np.asarray(
            make_sharded_pair_score_batched(mesh)(
                jnp.asarray(z), pp, jnp.int32(kb)
            )
        )
        np.testing.assert_allclose(got, ref, atol=2e-4)

    def test_mesh_host_bytes_flat_as_history_grows(self):
        """The mesh route's host->device traffic per suggest must be O(k),
        independent of history size — the VERDICT r4 #2 'done' gate."""
        from hyperopt_tpu import Domain
        from hyperopt_tpu.algos import tpe_device
        from hyperopt_tpu.base import JOB_STATE_DONE, STATUS_OK

        space = {
            "x": hp.uniform("x", -5, 5),
            "w": hp.quniform("w", 0, 100, 5),
        }
        domain = Domain(lambda c: c["x"] ** 2, space)
        mesh = default_mesh()
        rng = np.random.default_rng(0)

        def mk_doc(i):
            x = float(rng.uniform(-5, 5))
            w = float(np.round(rng.uniform(0, 100) / 5) * 5)
            return {
                "tid": i, "spec": None,
                "result": {"status": STATUS_OK, "loss": x * x},
                "misc": {"tid": i, "cmd": None,
                         "idxs": {"x": [i], "w": [i]},
                         "vals": {"x": [x], "w": [w]}},
                "state": JOB_STATE_DONE, "owner": None,
                "book_time": None, "refresh_time": None, "exp_key": None,
            }

        def per_suggest_bytes(n0, rounds=4):
            trials = Trials()
            trials._insert_trial_docs([mk_doc(i) for i in range(n0)])
            trials.refresh()
            # warm: first suggest pays the one-time full upload
            tpe.suggest([10**6], domain, trials, seed=1, mesh=mesh,
                        n_EI_candidates=128)
            dh = tpe_device.device_history_for(trials, domain.space, mesh=mesh)
            b0 = dh.bytes_uploaded
            for r in range(rounds):
                trials._insert_trial_docs([mk_doc(n0 + r)])
                trials.refresh()
                tpe.suggest([10**6 + r + 1], domain, trials, seed=2 + r,
                            mesh=mesh, n_EI_candidates=128)
            assert dh.full_rebuilds == 1, "append must stay incremental"
            return (dh.bytes_uploaded - b0) / rounds

        # capacities chosen inside one power-of-two bucket (1025..2045 and
        # 4097..8188) so no in-test bucket growth muddies the measurement
        small = per_suggest_bytes(1025)
        large = per_suggest_bytes(4097)
        assert small < 4096, small  # O(k) scalars, not the history
        assert large <= small * 1.5 + 256, (small, large)

    def test_mesh_and_device_paths_agree(self):
        """The unified route makes mesh vs single-device a SCORING-layout
        choice, not an algorithm fork: same seed -> same suggestions
        (same RNG keys, same fits; the sharded pair scorer's f32
        rounding does not flip the EI argmax on this seeded history)."""
        from hyperopt_tpu import Domain

        d = domains.get("branin")
        trials = Trials()
        fmin(
            d.fn, d.space, algo=rand.suggest, max_evals=30, trials=trials,
            rstate=np.random.default_rng(5), show_progressbar=False,
            verbose=False,
        )
        domain = Domain(d.fn, d.space)
        a = tpe.suggest([500, 501], domain, trials, seed=13, mesh=None,
                        n_EI_candidates=512)
        b = tpe.suggest([500, 501], domain, trials, seed=13,
                        mesh=default_mesh(), n_EI_candidates=512)
        for da, db in zip(a, b):
            assert da["misc"]["vals"] == db["misc"]["vals"]
