"""KS / total-variation conformance of samplers vs exact distributions
(reference parity: hyperopt/tests/test_rdists.py).

Both sampling paths are pinned to the same closed forms: the numpy
stochastic scope symbols AND the compiled JAX sampler.
"""

import numpy as np
import pytest
from scipy import stats

from hyperopt_tpu import hp
from hyperopt_tpu.rdists import (
    loguniform_gen,
    lognorm_tx_gen,
    qloguniform_gen,
    qlognormal_gen,
    qnormal_gen,
    quniform_gen,
)
from hyperopt_tpu.vectorize import CompiledSpace

N = 20000


def compiled_samples(node, n=N, seed=0):
    cs = CompiledSpace({"v": node})
    vals, _ = cs.sample_batch(seed, n)
    return np.asarray(vals["v"], dtype=float)


class TestContinuousKS:
    def test_uniform(self):
        x = compiled_samples(hp.uniform("v", -2.0, 5.0))
        assert stats.kstest(x, stats.uniform(loc=-2, scale=7).cdf).pvalue > 0.01

    def test_loguniform(self):
        low, high = np.log(1e-3), np.log(1e2)
        x = compiled_samples(hp.loguniform("v", low, high))
        assert stats.kstest(x, loguniform_gen(low, high).cdf).pvalue > 0.01

    def test_normal(self):
        x = compiled_samples(hp.normal("v", 3.0, 2.5))
        assert stats.kstest(x, stats.norm(loc=3, scale=2.5).cdf).pvalue > 0.01

    def test_lognormal(self):
        x = compiled_samples(hp.lognormal("v", 0.5, 0.8))
        assert stats.kstest(x, lognorm_tx_gen(0.5, 0.8).cdf).pvalue > 0.01


class TestQuantizedTV:
    """Total-variation distance between sampled freqs and the exact pmf."""

    def _tv_check(self, samples, dist, tol=0.02):
        vals, counts = np.unique(samples, return_counts=True)
        freq = counts / counts.sum()
        pmf = dist.pmf(vals)
        tv = 0.5 * np.abs(freq - pmf).sum() + 0.5 * max(0.0, 1.0 - pmf.sum())
        assert tv < tol, tv

    def test_quniform(self):
        x = compiled_samples(hp.quniform("v", 0.0, 10.0, 2.0))
        self._tv_check(x, quniform_gen(0.0, 10.0, 2.0))

    def test_qnormal(self):
        x = compiled_samples(hp.qnormal("v", 0.0, 3.0, 1.0))
        self._tv_check(x, qnormal_gen(0.0, 3.0, 1.0))

    def test_qloguniform(self):
        x = compiled_samples(hp.qloguniform("v", np.log(1.0), np.log(50.0), 5.0))
        self._tv_check(x, qloguniform_gen(np.log(1.0), np.log(50.0), 5.0))

    def test_qlognormal(self):
        x = compiled_samples(hp.qlognormal("v", 1.0, 0.7, 1.0))
        self._tv_check(x, qlognormal_gen(1.0, 0.7, 1.0))

    def test_numpy_path_agrees_too(self):
        from hyperopt_tpu.pyll import sample, scope

        x = sample(scope.qnormal(0.0, 3.0, 1.0, size=(N,)), np.random.default_rng(0))
        self._tv_check(x, qnormal_gen(0.0, 3.0, 1.0))


class TestExactForms:
    def test_loguniform_pdf_integrates(self):
        g = loguniform_gen(np.log(0.1), np.log(10.0))
        grid = np.linspace(0.1, 10.0, 20001)
        assert abs(np.trapezoid(g.pdf(grid), grid) - 1.0) < 1e-3

    def test_quniform_pmf_sums(self):
        g = quniform_gen(0.0, 10.0, 0.5)
        assert abs(g.pmf(g.support()).sum() - 1.0) < 1e-9

    def test_qnormal_pmf_sums(self):
        g = qnormal_gen(1.0, 2.0, 0.5)
        grid = np.arange(-20.0, 22.0, 0.5)
        assert abs(g.pmf(grid).sum() - 1.0) < 1e-6

    def test_pmf_zero_off_grid(self):
        g = quniform_gen(0.0, 10.0, 1.0)
        assert g.pmf(np.array([0.5, 1.3])).sum() == 0.0

    def test_rvs_seeded(self):
        g = qnormal_gen(0.0, 1.0, 0.5)
        a = g.rvs(size=10, random_state=3)
        b = g.rvs(size=10, random_state=3)
        np.testing.assert_array_equal(a, b)
