"""Search-health observability (ISSUE 8): fused-readback EI/Parzen
introspection, the SearchStats accumulator, the SH5xx health classifier,
its service surfaces (/v1/study_status health block, bounded per-study
/metrics gauges), and the no_progress_stop early-stop hook.
"""

import os
import sys

import numpy as np
import pytest

from hyperopt_tpu import Trials, hp
from hyperopt_tpu import diagnostics as sdiag
from hyperopt_tpu.base import JOB_STATE_DONE, STATUS_OK, Domain
from hyperopt_tpu.diagnostics import DIAG_COLS, SearchStats

_SCRIPTS = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "scripts"
)


def _study_report():
    sys.path.insert(0, _SCRIPTS)
    try:
        import study_report
    finally:
        try:
            sys.path.remove(_SCRIPTS)
        except ValueError:
            pass
    return study_report


def _done_doc(tid, vals, loss):
    return {
        "tid": tid, "spec": None,
        "result": {"status": STATUS_OK, "loss": loss},
        "misc": {
            "tid": tid, "cmd": None,
            "idxs": {k: [tid] for k in vals},
            "vals": {k: [v] for k, v in vals.items()},
        },
        "state": JOB_STATE_DONE, "owner": None, "book_time": None,
        "refresh_time": None, "exp_key": None,
    }


def _warm_trials(space, docs):
    domain = Domain(lambda c: 0.0, space)
    trials = Trials()
    trials._insert_trial_docs(docs)
    trials.refresh()
    return domain, trials


def _mixed_setup(n=12, seed=0):
    rng = np.random.default_rng(seed)
    space = {
        "x": hp.uniform("x", -5, 5),
        "lr": hp.loguniform("lr", -5, 0),
        "c": hp.choice("c", ["a", "b", "d"]),
    }
    docs = [
        _done_doc(i, {
            "x": float(rng.uniform(-5, 5)),
            "lr": float(np.exp(rng.uniform(-5, 0))),
            "c": int(rng.integers(3)),
        }, float(rng.normal()))
        for i in range(n)
    ]
    return _warm_trials(space, docs)


# ---------------------------------------------------------------------
# fused-readback introspection
# ---------------------------------------------------------------------


class TestFusedDiag:
    def test_suggest_publishes_snapshot(self):
        from hyperopt_tpu.algos import tpe

        domain, trials = _mixed_setup(n=12)
        sdiag.last_suggest_diag()  # clear any leftover
        tpe.suggest(
            [100], domain, trials, 7, n_startup_jobs=4,
            n_EI_candidates=64, verbose=False,
        )
        snap = sdiag.last_suggest_diag()
        assert snap is not None
        assert snap["n_below"] >= 1 and snap["n_eff"] == 12
        assert set(snap["labels"]) == {"x", "lr", "c"}
        for lb in ("x", "lr"):
            d = snap["labels"][lb]
            assert d["kind"] == "cont"
            assert d["nb"] + d["na"] <= 12
            assert d["nb"] >= 1
            assert d["ei_flatness"] is not None and d["ei_flatness"] >= 0
            assert 0.0 < d["ei_top_mass"] <= 1.0 + 1e-6
            assert d["sigma_min_rel"] is not None
            assert 0.0 <= d["sigma_floor_frac"] <= 1.0
        c = snap["labels"]["c"]
        assert c["kind"] == "idx"
        assert c["support"] == 3
        assert 1 <= c["n_distinct"] <= 3
        assert 0.0 <= c["dup_frac"] <= 1.0

    def test_snapshot_consumed_once(self):
        from hyperopt_tpu.algos import tpe

        domain, trials = _mixed_setup(n=12)
        tpe.suggest(
            [101], domain, trials, 8, n_startup_jobs=4,
            n_EI_candidates=32, verbose=False,
        )
        assert sdiag.last_suggest_diag() is not None
        assert sdiag.last_suggest_diag() is None  # consumed

    def test_disabled_publishes_nothing(self):
        from hyperopt_tpu.algos import tpe

        domain, trials = _mixed_setup(n=12)
        sdiag.last_suggest_diag()
        sdiag.set_enabled(False)
        try:
            tpe.suggest(
                [102], domain, trials, 9, n_startup_jobs=4,
                n_EI_candidates=32, verbose=False,
            )
            assert sdiag.last_suggest_diag() is None
        finally:
            sdiag.set_enabled(True)

    def test_resolver_diag_shape(self):
        """The async resolver exposes one [L, DIAG_COLS] row block per
        family request, aligned with the winner arrays."""
        from hyperopt_tpu.algos import tpe, tpe_device

        domain, trials = _mixed_setup(n=12)
        prep = tpe.suggest_prepare(
            [103], domain, trials, 11, n_startup_jobs=4,
            n_EI_candidates=32,
        )
        assert prep is not None
        resolve = tpe_device.multi_family_suggest_async(prep[0])
        outs = resolve()
        diags = resolve.diag
        assert len(diags) == len(outs)
        for win, diag in zip(outs, diags):
            assert diag.shape == (win.shape[0], DIAG_COLS)

    def test_zero_extra_dispatches_and_one_trace_budget(self):
        """THE zero-dispatch contract: the EI statistics ride the
        existing fused readback — M suggests produce exactly M profiled
        dispatches and stay inside the RecompilationAuditor's
        one-trace-per-(bucket, family) budget."""
        from hyperopt_tpu import profiling
        from hyperopt_tpu.algos import tpe
        from hyperopt_tpu.analysis import RecompilationAuditor
        from hyperopt_tpu.observability import DeviceStats

        domain, trials = _mixed_setup(n=12, seed=3)
        stats = DeviceStats()
        n = 6
        with profiling.DeviceProfiler(stats=stats):
            with RecompilationAuditor() as aud:
                for i in range(n):
                    tpe.suggest(
                        [200 + i], domain, trials, i, n_startup_jobs=4,
                        n_EI_candidates=64, verbose=False,
                    )
                    assert sdiag.last_suggest_diag() is not None
        assert stats.n_dispatches == n
        assert all(c == 1 for c in aud.trace_counts.values()), (
            aud.trace_counts
        )

    def test_batched_multi_study_diag_per_group(self):
        from hyperopt_tpu.algos import tpe, tpe_device

        da, ta = _mixed_setup(n=12, seed=0)
        db, tb = _mixed_setup(n=9, seed=1)
        kw = dict(n_startup_jobs=4, n_EI_candidates=32)
        prep_a = tpe.suggest_prepare([12], da, ta, 77, **kw)
        prep_b = tpe.suggest_prepare([9, 10], db, tb, 88, **kw)
        res_a, res_b = tpe_device.multi_study_suggest_async(
            [prep_a[0], prep_b[0]]
        )
        outs_b = res_b()
        outs_a = res_a()
        assert len(res_a.diag) == len(outs_a)
        assert len(res_b.diag) == len(outs_b)
        for win, diag in zip(outs_a, res_a.diag):
            assert diag.shape == (win.shape[0], DIAG_COLS)


# ---------------------------------------------------------------------
# SearchStats + classifier units (synthetic snapshots)
# ---------------------------------------------------------------------


def _cont_label(nb=10, na=30, flat=1.0, floor_frac=0.0):
    return {
        "kind": "cont", "nb": nb, "na": na, "ei_max": flat,
        "ei_flatness": flat, "ei_top_mass": 0.5,
        "sigma_min_rel": 0.2, "sigma_mean_rel": 0.5,
        "sigma_floor_frac": floor_frac,
    }


def _idx_label(nb=5, na=20, flat=1.0, n_distinct=2, support=3,
               dup_frac=0.0):
    return {
        "kind": "idx", "nb": nb, "na": na, "ei_max": flat,
        "ei_flatness": flat, "ei_top_mass": 0.5,
        "n_distinct": n_distinct, "dup_frac": dup_frac,
        "support": support,
    }


def _snap(labels):
    return {
        "n_below": 5, "gamma": 0.25, "n_eff": 40, "k": 1, "n_cand": 64,
        "labels": labels,
    }


def _fed(stats, n_ok=40, loss_fn=None):
    for i in range(n_ok):
        loss = loss_fn(i) if loss_fn else 100.0 - i
        stats.record_result(loss=loss, status="ok")
    return stats


class TestClassifier:
    def test_ok(self):
        s = _fed(SearchStats(n_startup_jobs=20, stall_window=50))
        s.record_suggest(_snap({"x": _cont_label()}))
        h = s.health()
        assert h["state"] == "OK" and h["rule"] == "SH500"
        assert h["rules"] == []

    def test_warmup_boundary(self):
        s = SearchStats(n_startup_jobs=20)
        _fed(s, n_ok=19)
        assert s.health()["rule"] == "SH501"
        s.record_result(loss=0.0, status="ok")
        assert s.health()["rule"] != "SH501"

    def test_stalled_and_improving(self):
        s = _fed(
            SearchStats(n_startup_jobs=10, stall_window=15),
            n_ok=40, loss_fn=lambda i: 5.0 if i > 10 else 100.0 - i,
        )
        h = s.health()
        assert h["rule"] == "SH502" and h["state"] == "STALLED"
        improving = _fed(
            SearchStats(n_startup_jobs=10, stall_window=15), n_ok=40
        )
        assert improving.health()["state"] == "OK"

    def test_flat_ei(self):
        s = _fed(SearchStats(n_startup_jobs=20, stall_window=100))
        s.record_suggest(_snap({"x": _cont_label(flat=0.01)}))
        h = s.health()
        assert h["rule"] == "SH503" and h["state"] == "FLAT_EI"

    def test_sigma_collapse(self):
        s = _fed(SearchStats(n_startup_jobs=20, stall_window=100))
        s.record_suggest(_snap({"x": _cont_label(floor_frac=0.9)}))
        h = s.health()
        assert h["rule"] == "SH504" and h["state"] == "SIGMA_COLLAPSE"

    def test_sigma_collapse_needs_enough_obs(self):
        s = _fed(SearchStats(n_startup_jobs=20, stall_window=100))
        s.record_suggest(
            _snap({"x": _cont_label(nb=3, floor_frac=1.0)})
        )
        assert s.health()["rule"] != "SH504"

    def test_space_exhausted_all_discrete_only(self):
        s = _fed(SearchStats(n_startup_jobs=20, stall_window=100))
        s.record_suggest(_snap({
            "c": _idx_label(n_distinct=3, support=3, dup_frac=1.0),
        }))
        assert s.health()["rule"] == "SH505"
        # a continuous dimension means the space is not enumerable
        s2 = _fed(SearchStats(n_startup_jobs=20, stall_window=100))
        s2.record_suggest(_snap({
            "c": _idx_label(n_distinct=3, support=3, dup_frac=1.0),
            "x": _cont_label(),
        }))
        assert s2.health()["rule"] != "SH505"

    def test_fault_degraded(self):
        s = SearchStats(n_startup_jobs=5, fault_min_results=8)
        for i in range(4):
            s.record_result(loss=float(i), status="ok")
        for _ in range(12):
            s.record_result(loss=float("nan"), status="ok")
        h = s.health()
        assert h["rule"] == "SH506" and h["state"] == "FAULT_DEGRADED"

    def test_priority_and_all_rules_reported(self):
        """A study can be simultaneously flat and stalled; priority
        gives FLAT_EI the state, but SH502 stays in the rule list (the
        early-stop hook depends on this)."""
        s = _fed(
            SearchStats(n_startup_jobs=10, stall_window=15),
            n_ok=40, loss_fn=lambda i: 5.0,
        )
        s.record_suggest(_snap({"x": _cont_label(flat=0.01)}))
        h = s.health()
        assert h["rule"] == "SH503"
        assert {r["rule"] for r in h["rules"]} >= {"SH502", "SH503"}

    def test_quarantine_counts_via_fault_stats(self):
        from hyperopt_tpu.observability import FaultStats

        fs = FaultStats()
        fs.record("trial_quarantined", 10)
        s = SearchStats(
            n_startup_jobs=5, fault_stats=fs, fault_min_results=8
        )
        for i in range(10):
            s.record_result(loss=float(i), status="ok")
        snap = s.snapshot()
        assert snap["faults"]["n_quarantined"] == 10
        assert s.health()["rule"] == "SH506"

    def test_observe_trials_counts_nan_and_errors(self):
        from hyperopt_tpu.base import JOB_STATE_ERROR

        domain, trials = _mixed_setup(n=6)
        bad = _done_doc(100, {"x": 0.0, "lr": 0.1, "c": 1}, float("nan"))
        err = _done_doc(101, {"x": 0.0, "lr": 0.1, "c": 1}, 0.0)
        err["state"] = JOB_STATE_ERROR
        trials._insert_trial_docs([bad, err])
        trials.refresh()
        s = SearchStats(n_startup_jobs=2)
        s.observe_trials(trials)
        snap = s.snapshot()
        assert snap["faults"]["n_nan"] == 1
        assert snap["faults"]["n_error"] == 1
        assert snap["n_ok"] == 6
        # idempotent re-observe
        s.observe_trials(trials)
        assert s.snapshot()["n_results"] == snap["n_results"]

    def test_regret_curve_and_optimum(self):
        s = SearchStats(n_startup_jobs=1, optimum=1.0)
        for loss in (5.0, 3.0, 4.0, 2.0):
            s.record_result(loss=loss, status="ok")
        snap = s.snapshot()
        assert snap["best_loss"] == 2.0
        assert snap["regret"] == pytest.approx(1.0)
        bests = [p["best"] for p in snap["regret_curve"]]
        assert bests == [5.0, 3.0, 2.0]  # improvements only
        assert bests == sorted(bests, reverse=True)


# ---------------------------------------------------------------------
# golden seeded fixtures (shared with scripts/study_report.py)
# ---------------------------------------------------------------------


class TestSeededFixtures:
    """One seeded fixture per SH5xx rule, single-sourced from the
    report script so the committed STUDY_HEALTH.json and the test
    suite can never disagree about what a fixture is."""

    @pytest.mark.parametrize(
        "name",
        [name for name, _, _ in (
            ("warmup_boundary", None, None),
            ("flat_ei_indistinct_choice", None, None),
            ("sigma_collapse_identical_best", None, None),
            ("exhausted_3_choice", None, None),
            ("nan_storm_objective", None, None),
        )],
    )
    def test_fixture_golden_rule(self, name):
        rep = _study_report()
        intended, fn = next(
            (rule, f) for n, rule, f in rep.FIXTURES if n == name
        )
        stats, extra = fn(quick=True)
        h = stats.health()
        assert h["rule"] == intended, (name, h)
        if name == "warmup_boundary":
            assert extra["past_boundary_state"] != "WARMUP"

    @pytest.mark.slow
    def test_stalled_fixture_golden_rule(self):
        rep = _study_report()
        _, rule, fn = next(
            x for x in rep.FIXTURES if x[0] == "stalled_plateau"
        )
        stats, _ = fn(quick=True)
        assert stats.health()["rule"] == rule


# ---------------------------------------------------------------------
# early stop
# ---------------------------------------------------------------------


class TestNoProgressStop:
    def _run(self, obj, stop_fn, seed, max_evals):
        from functools import partial

        from hyperopt_tpu import fmin
        from hyperopt_tpu.algos import tpe

        trials = Trials()
        fmin(
            obj, {"x": hp.uniform("x", -5, 5)},
            algo=partial(
                tpe.suggest, n_startup_jobs=8, n_EI_candidates=32
            ),
            max_evals=max_evals, trials=trials,
            rstate=np.random.default_rng(seed),
            show_progressbar=False, verbose=False,
            early_stop_fn=stop_fn,
        )
        return trials

    def test_halts_plateaued_study(self):
        from hyperopt_tpu.early_stop import no_progress_stop

        stop = no_progress_stop(
            iteration_stop_count=10, n_startup_jobs=8
        )
        trials = self._run(
            lambda c: max(abs(c["x"]), 2.0), stop, seed=1, max_evals=60
        )
        # halted well short of the budget, and past warmup + window
        assert 18 <= len(trials.trials) < 60
        assert any(
            r["rule"] == "SH502"
            for r in stop.search_stats.health()["rules"]
        )

    def test_never_halts_improving_study(self):
        from hyperopt_tpu.early_stop import no_progress_stop

        cnt = {"n": 0}

        def improving(c):
            cnt["n"] += 1
            return 100.0 - cnt["n"]

        stop = no_progress_stop(
            iteration_stop_count=10, n_startup_jobs=8
        )
        trials = self._run(improving, stop, seed=2, max_evals=40)
        assert len(trials.trials) == 40


# ---------------------------------------------------------------------
# service surfaces
# ---------------------------------------------------------------------


SPACE = {"x": hp.uniform("x", -5, 5), "c": hp.choice("c", [0, 1, 2])}


class TestServiceSurfaces:
    def _drive(self, svc, study_id="s", n_trials=12, seed=0):
        rng = np.random.default_rng(seed)
        svc.create_study(study_id, SPACE, seed=seed, algo="tpe",
                         algo_params={"n_startup_jobs": 4})
        for _ in range(n_trials):
            (t,) = svc.suggest(study_id, n=1)
            svc.report(study_id, t["tid"], loss=float(rng.normal()))

    def test_study_status_health_block(self):
        from hyperopt_tpu.service.core import OptimizationService

        svc = OptimizationService()
        try:
            self._drive(svc, n_trials=12)
            st = svc.study_status("s")
            assert st["seed_cursor"]["drawn"] == 12
            assert st["seed_cursor"]["committed"] == 12
            f = st["faults"]
            assert f["n_error"] == 0 and f["n_nan"] == 0
            assert f["fault_rate"] == 0.0
            h = st["health"]
            assert h["state"] in ("OK", "STALLED")
            assert h["n_results"] == 12
            assert h["best_loss"] is not None
            # the fused snapshot made it through the batched scheduler
            assert h["last_suggest"] is not None
            assert set(h["last_suggest"]["labels"]) == {"x", "c"}
        finally:
            svc.close(timeout=5)

    def test_nan_report_rejected_but_counted(self):
        from hyperopt_tpu.service.core import OptimizationService

        svc = OptimizationService()
        try:
            svc.create_study("n", SPACE, seed=0, algo="rand")
            (t,) = svc.suggest("n", n=1)
            with pytest.raises(ValueError):
                svc.report("n", t["tid"], loss=float("nan"))
            # an idempotent client retrying the rejected report must
            # not double-count the one diverged trial
            with pytest.raises(ValueError):
                svc.report("n", t["tid"], loss=float("nan"))
            st = svc.study_status("n")
            assert st["faults"]["n_nan"] == 1
        finally:
            svc.close(timeout=5)

    def test_error_reports_degrade_health(self):
        from hyperopt_tpu.service.core import OptimizationService

        svc = OptimizationService()
        try:
            svc.create_study("e", SPACE, seed=0, algo="rand",
                             algo_params=None)
            for _ in range(10):
                (t,) = svc.suggest("e", n=1)
                svc.report("e", t["tid"], status="fail")
            st = svc.study_status("e")
            assert st["faults"]["n_error"] == 10
            # rand has no n_startup_jobs param; default warmup (20)
            # still owns the state, but SH506 must be in the rule list
            rules = {r["rule"] for r in st["health"]["rules"]}
            assert "SH506" in rules
        finally:
            svc.close(timeout=5)

    def test_metrics_gauges_and_cardinality_guard(self):
        """Per-study gauge families are bounded at metrics_max_studies
        (top-N by recency) and the truncation counter accounts for the
        dropped studies — the million-study /metrics regression."""
        from hyperopt_tpu.service.core import OptimizationService

        svc = OptimizationService(metrics_max_studies=3)
        try:
            for i in range(5):
                svc.create_study(f"s{i}", SPACE, seed=i, algo="rand")
                (t,) = svc.suggest(f"s{i}", n=1)
                svc.report(f"s{i}", t["tid"], loss=float(i))
            text = svc.metrics_text()
            lines = text.splitlines()
            health_lines = [
                ln for ln in lines
                if ln.startswith("hyperopt_study_health{")
            ]
            assert len(health_lines) == 3
            studies = {
                ln.split('study="')[1].split('"')[0]
                for ln in lines if 'study="' in ln
                and ln.startswith("hyperopt_study_")
            }
            assert len(studies) == 3
            # recency bound: the LAST-active studies survive
            assert studies == {"s2", "s3", "s4"}
            trunc = [
                ln for ln in lines
                if ln.startswith("hyperopt_studies_truncated_total")
                and not ln.startswith("#")
            ]
            assert trunc and float(trunc[0].split()[-1]) >= 2.0
            for gauge in ("hyperopt_study_best_loss{",
                          "hyperopt_study_ei_flatness{",
                          "hyperopt_study_gamma{",
                          "hyperopt_study_n_below{",
                          "hyperopt_study_ei_max{",
                          "hyperopt_study_regret{"):
                assert any(ln.startswith(gauge) for ln in lines), gauge
        finally:
            svc.close(timeout=5)

    def test_health_attr_on_suggest_span(self):
        from hyperopt_tpu import tracing
        from hyperopt_tpu.service.core import OptimizationService

        tracer = tracing.Tracer(sample=1.0)
        svc = OptimizationService(tracer=tracer)
        try:
            svc.create_study("h", SPACE, seed=0, algo="tpe",
                             algo_params={"n_startup_jobs": 2})
            captured = []
            orig_finish = tracer.finish

            def capture(trace):
                if trace is not None:
                    captured.append(trace)
                return orig_finish(trace)

            tracer.finish = capture
            svc.suggest("h", n=1)
            roots = [
                t.root for t in captured
                if t.root is not None and t.root.name == "service.suggest"
            ]
            assert roots
            attrs = roots[-1].attrs or {}
            assert attrs.get("health") in sdiag.HEALTH_STATES
            assert str(attrs.get("health_rule", "")).startswith("SH5")
        finally:
            svc.close(timeout=5)


# ---------------------------------------------------------------------
# prometheus shape + lint registration
# ---------------------------------------------------------------------


def test_render_prometheus_study_health_shape():
    from hyperopt_tpu.observability import render_prometheus

    text = render_prometheus(study_health={
        "rows": [{
            "study": "a", "best_loss": 1.5, "regret": 0.5,
            "gamma": 0.25, "n_below": 4, "ei_max": 2.0,
            "ei_flatness": 0.7, "state": "OK",
        }],
        "truncated_total": 7,
    })
    assert 'hyperopt_study_best_loss{study="a"} 1.5' in text
    assert 'hyperopt_study_health{state="OK",study="a"} 1' in text
    assert "hyperopt_studies_truncated_total 7" in text
    # a study with no optimum declared renders NaN, not a crash
    text2 = render_prometheus(study_health={
        "rows": [{
            "study": "b", "best_loss": None, "regret": None,
            "gamma": None, "n_below": None, "ei_max": None,
            "ei_flatness": None, "state": "WARMUP",
        }],
        "truncated_total": 0,
    })
    assert 'hyperopt_study_best_loss{study="b"} NaN' in text2


def test_diagnostics_registered_in_race_lint():
    from hyperopt_tpu.analysis import discover_race_files, lint_races

    paths = [p for p in discover_race_files() if p.endswith("diagnostics.py")]
    assert paths, "diagnostics.py must be race-linted"
    assert lint_races(paths=paths) == []
