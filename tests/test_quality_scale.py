"""Candidate-scale quality guard (VERDICT r4 #4).

`scripts/quality_study.py` (committed results: QUALITY.md / QUALITY.json)
measures whether raising ``n_EI_candidates`` buys optimization quality.
These tests guard the HEADLINE configuration — the TPU-default large
candidate count — against silent quality regressions: scoring 8192
candidates must still optimize (the EI argmax over a huge sample of
l(x) draws must not wander into pathological tails), on both the
single-device and mesh paths.
"""

from functools import partial

import numpy as np

from hyperopt_tpu import Trials, fmin
from hyperopt_tpu.algos import tpe
from hyperopt_tpu.models import domains


def _best(dname, n_cand, seed, mesh=None, max_evals=40):
    d = domains.get(dname)
    trials = Trials()
    fmin(
        d.fn, d.space,
        algo=partial(tpe.suggest, n_EI_candidates=n_cand, mesh=mesh),
        max_evals=max_evals, trials=trials,
        rstate=np.random.default_rng(seed),
        show_progressbar=False, verbose=False,
    )
    return min(l for l in trials.losses() if l is not None and not np.isnan(l))


def test_headline_candidate_count_still_optimizes():
    """quadratic1 at c=8192 must meet the domain's own quality threshold
    (the same bar the c=24 default is held to in test_tpe.py)."""
    d = domains.get("quadratic1")
    vals = [_best("quadratic1", 8192, s, max_evals=d.quality_evals) for s in (0, 1)]
    assert float(np.mean(vals)) < d.quality_threshold, vals


def test_candidate_scale_not_catastrophic_on_mesh():
    """Mesh path at c=8192: same threshold bar, sharded scoring."""
    from hyperopt_tpu.parallel.sharding import default_mesh

    d = domains.get("quadratic1")
    best = _best("quadratic1", 8192, 3, mesh=default_mesh(),
                 max_evals=d.quality_evals)
    assert best < d.quality_threshold, best
