"""FileTrials durable-queue tests.

Reference parity (SURVEY.md §4 Mongo row): the reference tests distributed
mode as (real mongod subprocess × threaded in-process workers); here it is
(real filesystem queue × threaded in-process workers): reservation
exclusivity, worker error handling, durability/resume, attachments, CLI
parsing.
"""

import os
import sys
import threading
import time

import numpy as np
import pytest

from hyperopt_tpu import Trials, fmin, hp
from hyperopt_tpu.algos import rand
from hyperopt_tpu.base import (
    JOB_STATE_DONE,
    JOB_STATE_ERROR,
    JOB_STATE_NEW,
    JOB_STATE_RUNNING,
    STATUS_OK,
)
from hyperopt_tpu.parallel.file_trials import FileJobs, FileTrials
from hyperopt_tpu.parallel.worker import (
    FileWorker,
    ReserveTimeout,
    main_worker_helper,
    make_parser,
)


def quad_objective(cfg):
    return (cfg["x"] - 3) ** 2


SPACE = {"x": hp.uniform("x", -5, 5)}


def run_workers(queue_dir, n_workers=2, max_jobs=1000):
    """Threaded in-process workers (the reference's with_worker_threads).

    Returns ``(threads, stop)``.  Workers poll THROUGH ``ReserveTimeout``
    until ``stop`` is set: a transiently empty queue (the fmin driver
    thread descheduled on a loaded single-core box) must not make a
    worker exit for good while fmin still has trials to enqueue —
    with every worker gone, fmin's poll loop blocks forever (observed
    as a suite deadlock in test_worker_error_path).  Call sites set
    ``stop`` once fmin returns, then join.
    """
    stop = threading.Event()

    def loop():
        w = FileWorker(queue_dir, poll_interval=0.02)
        done = 0
        while done < max_jobs and not stop.is_set():
            try:
                w.run_one(reserve_timeout=0.5)
                done += 1
            except ReserveTimeout:
                continue
            except Exception:
                # e.g. the tmp_path queue dir was deleted after a test
                # failure leaked this thread: don't busy-spin on it
                time.sleep(0.05)

    threads = [threading.Thread(target=loop, daemon=True) for _ in range(n_workers)]
    for t in threads:
        t.start()
    return threads, stop


class TestFileJobs:
    def test_id_allocation_monotonic_across_instances(self, tmp_path):
        j1 = FileJobs(str(tmp_path))
        j2 = FileJobs(str(tmp_path))
        assert j1.new_trial_ids(3) == [0, 1, 2]
        assert j2.new_trial_ids(2) == [3, 4]

    def test_reserve_exclusive(self, tmp_path):
        jobs = FileJobs(str(tmp_path))
        doc = {
            "tid": 0, "state": JOB_STATE_NEW, "spec": None,
            "result": {"status": "new"},
            "misc": {"tid": 0, "cmd": None, "idxs": {"x": [0]}, "vals": {"x": [1.0]}},
            "exp_key": None, "owner": None, "book_time": None, "refresh_time": None,
        }
        jobs.insert(doc)
        a = jobs.reserve("w1")
        b = jobs.reserve("w2")
        assert a is not None and a["owner"] == "w1"
        assert b is None

    def test_reserve_race_many_threads(self, tmp_path):
        jobs = FileJobs(str(tmp_path))
        for tid in range(20):
            jobs.insert({
                "tid": tid, "state": JOB_STATE_NEW, "spec": None,
                "result": {"status": "new"},
                "misc": {"tid": tid, "cmd": None, "idxs": {}, "vals": {}},
                "exp_key": None, "owner": None, "book_time": None, "refresh_time": None,
            })
        claimed = []
        lock = threading.Lock()

        def grab(owner):
            me = FileJobs(str(tmp_path))
            while True:
                doc = me.reserve(owner)
                if doc is None:
                    return
                with lock:
                    claimed.append(doc["tid"])

        threads = [threading.Thread(target=grab, args=(f"w{i}",)) for i in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert sorted(claimed) == list(range(20))  # every job exactly once

    def test_requeue_stale(self, tmp_path):
        jobs = FileJobs(str(tmp_path))
        jobs.insert({
            "tid": 0, "state": JOB_STATE_NEW, "spec": None,
            "result": {"status": "new"},
            "misc": {"tid": 0, "cmd": None, "idxs": {}, "vals": {}},
            "exp_key": None, "owner": None, "book_time": None, "refresh_time": None,
        })
        jobs.reserve("dead-worker")
        assert jobs.reserve("w2") is None
        n = jobs.requeue_stale(max_age_secs=-1.0)  # everything is stale
        assert n == 1
        again = jobs.reserve("w2")
        assert again is not None and again["owner"] == "w2"

    def test_attachments_roundtrip(self, tmp_path):
        jobs = FileJobs(str(tmp_path))
        jobs.set_attachment("blob", b"\x00\x01data")
        assert jobs.get_attachment("blob") == b"\x00\x01data"
        assert jobs.has_attachment("blob")
        jobs.del_attachment("blob")
        assert not jobs.has_attachment("blob")


class TestFileTrialsFmin:
    def test_fmin_with_threaded_workers(self, tmp_path):
        trials = FileTrials(str(tmp_path / "q"))
        threads, stop = run_workers(str(tmp_path / "q"), n_workers=3)
        try:
            best = fmin(
                quad_objective, SPACE, algo=rand.suggest, max_evals=20,
                trials=trials, rstate=np.random.default_rng(0),
                show_progressbar=False, verbose=False,
            )
        finally:
            stop.set()
        for t in threads:
            t.join(timeout=5)
        assert len(trials) == 20
        assert all(t["state"] == JOB_STATE_DONE for t in trials.trials)
        assert abs(best["x"] - 3) < 2.0
        owners = {t["owner"] for t in trials.trials}
        assert owners  # stamped by workers

    def test_durability_resume(self, tmp_path):
        qdir = str(tmp_path / "q")
        trials = FileTrials(qdir)
        threads, stop = run_workers(qdir, n_workers=2)
        try:
            fmin(
                quad_objective, SPACE, algo=rand.suggest, max_evals=10,
                trials=trials, rstate=np.random.default_rng(0),
                show_progressbar=False, verbose=False,
            )
        finally:
            stop.set()
        for t in threads:
            t.join(timeout=5)
        # a brand-new store on the same dir sees everything (Mongo-style
        # durability); resuming fmin continues to 15
        trials2 = FileTrials(qdir)
        assert len(trials2) == 10
        threads, stop = run_workers(qdir, n_workers=2)
        try:
            fmin(
                quad_objective, SPACE, algo=rand.suggest, max_evals=15,
                trials=trials2, rstate=np.random.default_rng(1),
                show_progressbar=False, verbose=False,
            )
        finally:
            stop.set()
        for t in threads:
            t.join(timeout=5)
        assert len(FileTrials(qdir)) == 15

    def test_worker_error_path(self, tmp_path):
        qdir = str(tmp_path / "q")
        trials = FileTrials(qdir)

        threads, stop = run_workers(qdir, n_workers=2)
        try:
            fmin(
                flaky_objective, SPACE, algo=rand.suggest, max_evals=12,
                trials=trials, catch_eval_exceptions=True,
                rstate=np.random.default_rng(3), show_progressbar=False,
                verbose=False, return_argmin=False,
            )
        finally:
            stop.set()
        for t in threads:
            t.join(timeout=5)
        trials.refresh()
        states = [t["state"] for t in trials._dynamic_trials]
        assert JOB_STATE_ERROR in states and JOB_STATE_DONE in states
        errs = [
            t for t in trials._dynamic_trials if t["state"] == JOB_STATE_ERROR
        ]
        assert all("negative" in t["misc"]["error"][1] for t in errs)


class TestWorkerCLI:
    def test_parser_defaults(self):
        opts = make_parser().parse_args(["--queue", "/tmp/q"])
        assert opts.queue == "/tmp/q"
        assert opts.poll_interval == 1.0
        assert opts.max_consecutive_failures == 4
        assert opts.reserve_timeout == 120.0
        assert opts.workdir is None

    def test_parser_all_flags(self):
        opts = make_parser().parse_args(
            [
                "--queue", "/q", "--exp-key", "e1", "--poll-interval", "0.5",
                "--max-consecutive-failures", "2", "--reserve-timeout", "10",
                "--workdir", "/w", "--last-job-timeout", "60", "--max-jobs", "5",
            ]
        )
        assert opts.exp_key == "e1"
        assert opts.max_jobs == 5

    def test_main_worker_helper_drains_queue(self, tmp_path):
        qdir = str(tmp_path / "q")
        trials = FileTrials(qdir)
        # enqueue trials by running fmin in a thread (it blocks until done)
        t = threading.Thread(
            target=lambda: fmin(
                quad_objective, SPACE, algo=rand.suggest, max_evals=5,
                trials=trials, rstate=np.random.default_rng(0),
                show_progressbar=False, verbose=False, return_argmin=False,
            ),
            daemon=True,
        )
        t.start()
        opts = make_parser().parse_args(
            ["--queue", qdir, "--poll-interval", "0.02", "--reserve-timeout", "2"]
        )
        rc = main_worker_helper(opts)
        t.join(timeout=10)
        assert rc == 0
        assert len(FileTrials(qdir)) == 5

    def test_worker_ctrl_checkpoint(self, tmp_path):
        qdir = str(tmp_path / "q")
        trials = FileTrials(qdir)

        threads, stop = run_workers(qdir, n_workers=1)
        try:
            fmin(
                checkpointing_objective, SPACE, algo=rand.suggest, max_evals=2,
                trials=trials, rstate=np.random.default_rng(0),
                show_progressbar=False, verbose=False, return_argmin=False,
                pass_expr_memo_ctrl=None,
            )
        finally:
            stop.set()
        for t in threads:
            t.join(timeout=5)
        assert len(FileTrials(qdir)) == 2


def checkpointing_objective(cfg):
    return abs(cfg["x"])


def flaky_objective(cfg):
    if cfg["x"] < 0:
        raise RuntimeError("negative")
    return cfg["x"]


class TestUnlockAtomicity:
    """Rename-then-verify unlock (the read->unlink TOCTOU fix)."""

    def test_unlock_own_lock(self, tmp_path):
        from hyperopt_tpu.parallel.file_trials import FileJobs

        jobs = FileJobs(str(tmp_path / "q"))
        lock = str(tmp_path / "q" / "locks" / "0.lock")
        assert jobs._try_lock(lock, "me")
        assert jobs._unlock_if_owner(lock, "me") is True
        assert not os.path.exists(lock)

    def test_unlock_preserves_foreign_lock(self, tmp_path):
        from hyperopt_tpu.parallel.file_trials import FileJobs

        jobs = FileJobs(str(tmp_path / "q"))
        lock = str(tmp_path / "q" / "locks" / "1.lock")
        assert jobs._try_lock(lock, "them")
        assert jobs._unlock_if_owner(lock, "me") is False
        # their reservation survives, content intact, no stray temp files
        assert os.path.exists(lock)
        with open(lock) as f:
            assert f.read() == "them"
        leftovers = [p for p in os.listdir(tmp_path / "q" / "locks")
                     if ".unlock." in p]
        assert leftovers == []

    def test_unlock_missing_lock(self, tmp_path):
        from hyperopt_tpu.parallel.file_trials import FileJobs

        jobs = FileJobs(str(tmp_path / "q"))
        lock = str(tmp_path / "q" / "locks" / "2.lock")
        assert jobs._unlock_if_owner(lock, "me") is False


class TestSubprocessWorkers:
    """True cross-process E2E: the worker CLI in separate interpreters,
    mutual exclusion via the on-disk lock files (threads share a GIL and
    an inode cache; processes do not)."""

    def test_fmin_with_subprocess_workers(self, tmp_path):
        import subprocess
        import sys

        from worker_objective_helper import quad_objective as proc_objective

        qdir = str(tmp_path / "q")
        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        env = dict(os.environ)
        env["PYTHONPATH"] = os.pathsep.join(
            [repo, os.path.join(repo, "tests")]
            + ([env["PYTHONPATH"]] if env.get("PYTHONPATH") else [])
        )
        env["JAX_PLATFORMS"] = "cpu"
        logs, procs = [], []

        def worker_logs():
            out = []
            for i, f in enumerate(logs):
                f.flush()
                f.seek(0)
                out.append(f"--- worker {i} (rc={procs[i].poll()}) ---\n" + f.read())
            return "\n".join(out)

        try:
            for i in range(2):
                logs.append(open(tmp_path / f"worker{i}.log", "w+"))
                procs.append(
                    subprocess.Popen(
                        [
                            sys.executable, "-m", "hyperopt_tpu.parallel.worker",
                            "--queue", qdir,
                            "--poll-interval", "0.05",
                            "--reserve-timeout", "20",
                            "--workdir", str(tmp_path / f"w{i}"),
                        ],
                        env=env,
                        cwd=repo,
                        stdout=logs[i],
                        stderr=subprocess.STDOUT,
                    )
                )
            trials = FileTrials(qdir)
            # fmin's own whole-run timeout is the watchdog: dead workers
            # leave jobs NEW and the loop exits instead of polling forever
            best = fmin(
                proc_objective, SPACE, algo=rand.suggest, max_evals=12,
                trials=trials, rstate=np.random.default_rng(0),
                show_progressbar=False, verbose=False, timeout=90,
            )
            trials.refresh()
            assert len(trials) == 12, worker_logs()
            assert all(
                t["state"] == JOB_STATE_DONE for t in trials.trials
            ), worker_logs()
            assert abs(best["x"] - 3) < 2.5
            # every trial executed exactly once, by a real worker process
            # (owner stamped host:pid at reservation); with 2 workers the
            # split is usually but not deterministically 2-way, so only
            # the stamping itself is asserted
            owners = {t["owner"] for t in trials.trials}
            assert owners and all(o for o in owners), owners
        finally:
            for p in procs:
                p.terminate()
            for p in procs:
                p.wait(timeout=10)
            for f in logs:
                f.close()


class TestCrashInjection:
    """SIGKILL a REAL worker process mid-reservation and mid-result-write
    (VERDICT r4 #8): requeue_stale must recover the trial exactly once,
    no doc lost, none double-run — the recovery the reference's Mongo
    backend lacks (dead workers leave jobs reserved forever,
    hyperopt/mongoexp.py reserve semantics ~L160-500).
    """

    REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

    def _spawn(self, code, qdir, ready):
        import subprocess

        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        proc = subprocess.Popen(
            [sys.executable, "-c", code],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, env=env,
        )
        deadline = time.time() + 60
        while not os.path.exists(ready):
            if proc.poll() is not None:
                raise AssertionError(
                    "crash child exited early:\n"
                    + proc.stdout.read().decode(errors="replace")
                )
            assert time.time() < deadline, "crash child never became ready"
            time.sleep(0.05)
        return proc

    def _seed_queue(self, qdir, backend=None):
        jobs = FileJobs(qdir, backend=backend)
        jobs.insert({
            "tid": 0, "state": JOB_STATE_NEW, "spec": None,
            "result": {"status": "new"},
            "misc": {"tid": 0, "cmd": None, "idxs": {"x": [0]}, "vals": {"x": [1.0]}},
            "exp_key": None, "owner": None, "book_time": None,
            "refresh_time": None,
        })
        return jobs

    def _assert_recovers_exactly_once(self, jobs, qdir):
        import signal

        # the dead worker's claim is visible: RUNNING + lock file held
        [doc] = jobs.all_docs()
        assert doc["state"] == JOB_STATE_RUNNING
        assert os.path.exists(jobs.lock_path(0))
        # a live worker cannot steal it before recovery
        assert jobs.reserve("thief") is None
        # recovery: exactly one requeue; doc intact and NEW again
        assert jobs.requeue_stale(max_age_secs=-1.0) == 1
        assert jobs.requeue_stale(max_age_secs=-1.0) == 0  # idempotent
        [doc] = jobs.all_docs()
        assert doc["state"] == JOB_STATE_NEW and doc["owner"] is None
        # a second worker runs it to completion, exactly once
        doc = jobs.reserve("rescuer")
        assert doc is not None and doc["owner"] == "rescuer"
        doc["state"] = JOB_STATE_DONE
        doc["result"] = {"status": "ok", "loss": 0.5}
        jobs.write(doc)
        docs = jobs.all_docs()
        assert len(docs) == 1  # no doc lost, none duplicated
        assert docs[0]["state"] == JOB_STATE_DONE
        assert docs[0]["result"]["loss"] == 0.5
        assert docs[0]["owner"] == "rescuer"

    def test_sigkill_mid_reservation(self, tmp_path):
        import signal

        qdir = str(tmp_path / "q")
        ready = str(tmp_path / "ready")
        jobs = self._seed_queue(qdir)
        code = f"""
import sys, time
sys.path.insert(0, {self.REPO!r})
from hyperopt_tpu.parallel.file_trials import FileJobs
jobs = FileJobs({qdir!r})
doc = jobs.reserve("crash-worker")
assert doc is not None, "nothing to reserve"
open({ready!r}, "w").write(str(doc["tid"]))
time.sleep(300)  # SIGKILLed here, reservation held
"""
        proc = self._spawn(code, qdir, ready)
        os.kill(proc.pid, signal.SIGKILL)
        proc.wait()
        self._assert_recovers_exactly_once(jobs, qdir)

    def test_sigkill_mid_result_write(self, tmp_path):
        import signal

        qdir = str(tmp_path / "q")
        ready = str(tmp_path / "ready")
        # the per-doc backend: this test pins the atomic-replace torn-write
        # window (os.replace stubbed below), which the segmented backend
        # replaces with the torn-segment-tail discipline (test_fsck FS410)
        jobs = self._seed_queue(qdir, backend="doc")
        # the child stalls INSIDE the result write: tmp file written and
        # fsynced, the atomic os.replace not yet executed — the kill lands
        # exactly in the torn-write window
        code = f"""
import sys, time, os
sys.path.insert(0, {self.REPO!r})
from hyperopt_tpu.parallel import file_trials as ft
jobs = ft.FileJobs({qdir!r})
doc = jobs.reserve("crash-worker-2")
assert doc is not None, "nothing to reserve"
doc["state"] = {JOB_STATE_DONE}
doc["result"] = {{"status": "ok", "loss": 99.0}}
def hang(src, dst):
    open({ready!r}, "w").write("mid-write")
    time.sleep(300)  # SIGKILLed here, replace pending
ft.os.replace = hang
jobs.write(doc)
"""
        proc = self._spawn(code, qdir, ready)
        os.kill(proc.pid, signal.SIGKILL)
        proc.wait()
        # atomicity held: the doc is the PRE-write (RUNNING) version, not
        # a torn file, and the dead worker's phantom result never lands
        self._assert_recovers_exactly_once(jobs, qdir)
        # the orphaned tmp file (if any) must not confuse the queue scan
        assert jobs.count_states()[JOB_STATE_DONE] == 1


# ---------------------------------------------------------------------
# Randomized concurrency property (fuzz-campaign property 4)
# ---------------------------------------------------------------------

# Per-run evaluation-count registry, keyed by the run's queue dir: a
# worker thread leaked from a previous wedged run (e.g. after the fuzz
# campaign's watchdog fired mid-run) records into ITS OWN run's dict and
# can no longer corrupt a later seed's exactly-once accounting the way a
# single module-global dict could (ADVICE r5).
_chaos_registry = {}
_chaos_registry_lock = threading.Lock()


class ChaosObjective:
    """Random-latency, randomly-failing objective that records how many
    times each sampled point was evaluated (uid = the x draw, unique per
    trial with probability 1 under a continuous dist).  A picklable class
    instance (FileTrials workers unpickle the domain) carrying its run
    key; the counts dict itself stays process-local in the registry."""

    def __init__(self, run_key):
        self.run_key = run_key

    @property
    def counts(self):
        with _chaos_registry_lock:
            return _chaos_registry.setdefault(self.run_key, {})

    def __call__(self, cfg):
        uid = round(float(cfg["x"]), 9)
        counts = self.counts
        with _chaos_registry_lock:
            counts[uid] = counts.get(uid, 0) + 1
        time.sleep(float(cfg["sleep_ms"]) / 1000.0)
        if cfg["fail"]:
            raise RuntimeError("chaos failure")
        return (float(cfg["x"]) - 1.0) ** 2


@pytest.mark.parametrize("seed", range(2))
def test_fuzzed_filetrials_concurrency(seed):
    """Randomized end-to-end stress of the durable queue: random worker
    count, per-trial latency, and failure rate.  Invariants: no doc is
    lost, every doc reaches a terminal state exactly once (double
    evaluation would be a reservation-exclusivity bug; the SIGKILL tier
    covers crashed-worker recovery separately), failures carry their
    error annotation, and successes carry a finite loss.  Also looped
    over hundreds of fresh seeds by scripts/fuzz_campaign.py."""
    import tempfile

    rng = np.random.default_rng(10_000 + seed)
    n_workers = int(rng.integers(1, 5))
    n_trials = int(rng.integers(8, 21))
    fail_p = float(rng.uniform(0.0, 0.35))
    max_sleep_ms = float(rng.choice([5.0, 30.0, 80.0]))
    space = {
        "x": hp.uniform("x", -5, 5),
        "sleep_ms": hp.uniform("sleep_ms", 0.0, max_sleep_ms),
        "fail": hp.pchoice("fail", [(1.0 - fail_p, 0), (fail_p, 1)]),
    }

    with tempfile.TemporaryDirectory() as td:
        qdir = os.path.join(td, "q")
        objective = ChaosObjective(qdir)  # qdir is unique per run
        trials = FileTrials(qdir)
        threads, stop = run_workers(qdir, n_workers=n_workers)
        try:
            fmin(
                objective, space, algo=rand.suggest,
                max_evals=n_trials, trials=trials,
                catch_eval_exceptions=True,
                rstate=np.random.default_rng(seed),
                show_progressbar=False, verbose=False, return_argmin=False,
            )
        finally:
            # join INSIDE the finally: if fmin raises (e.g. the campaign
            # watchdog's TimeoutError), live workers must be drained
            # before TemporaryDirectory cleanup, or rmtree races their
            # in-flight writes and masks the original failure
            stop.set()
            for t in threads:
                t.join(timeout=10)
        trials.refresh()
        docs = trials._dynamic_trials
        assert len(docs) == n_trials, (len(docs), n_trials)
        assert len({d["tid"] for d in docs}) == n_trials
        for d in docs:
            assert d["state"] in (JOB_STATE_DONE, JOB_STATE_ERROR), d["tid"]
            if d["state"] == JOB_STATE_DONE:
                assert np.isfinite(d["result"]["loss"])
            else:
                assert "chaos failure" in d["misc"]["error"][1]
            assert d["owner"] is not None
        with _chaos_registry_lock:
            counts = dict(_chaos_registry.get(qdir, {}))
        assert len(counts) == n_trials
        multi = {u: c for u, c in counts.items() if c != 1}
        assert not multi, f"trials evaluated more than once: {multi}"
