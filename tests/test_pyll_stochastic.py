"""Statistical tests of the stochastic scope symbols + sample().

Mirrors the reference's sample-histogram style checks (SURVEY.md §4).
"""

import numpy as np
import pytest

from hyperopt_tpu.pyll import as_apply, rec_eval, sample, scope
from hyperopt_tpu.pyll.stochastic import recursive_set_rng_kwarg


RNG = lambda: np.random.default_rng(42)


def test_sample_uniform_range():
    draws = np.array([sample(scope.uniform(-2.0, 3.0), RNG()) for _ in range(1)])
    big = sample(scope.uniform(-2.0, 3.0, size=(10000,)), RNG())
    assert big.shape == (10000,)
    assert big.min() >= -2.0 and big.max() < 3.0
    assert abs(big.mean() - 0.5) < 0.1


def test_sample_loguniform_support():
    big = sample(scope.loguniform(np.log(1e-3), np.log(1e2), size=(5000,)), RNG())
    assert big.min() >= 1e-3 and big.max() <= 1e2
    # log of draws should be uniform
    logs = np.log(big)
    assert abs(logs.mean() - (np.log(1e-3) + np.log(1e2)) / 2) < 0.2


def test_sample_quniform_grid():
    big = sample(scope.quniform(0.0, 10.0, 0.5, size=(2000,)), RNG())
    assert np.allclose(np.round(big / 0.5) * 0.5, big)


def test_sample_qloguniform_grid():
    big = sample(scope.qloguniform(np.log(1.0), np.log(100.0), 2.0, size=(2000,)), RNG())
    assert np.allclose(np.round(big / 2.0) * 2.0, big)
    assert big.min() >= 0.0


def test_sample_normal_moments():
    big = sample(scope.normal(5.0, 2.0, size=(20000,)), RNG())
    assert abs(big.mean() - 5.0) < 0.1
    assert abs(big.std() - 2.0) < 0.1


def test_sample_qnormal_grid():
    big = sample(scope.qnormal(0.0, 3.0, 1.0, size=(2000,)), RNG())
    assert np.allclose(np.round(big), big)


def test_sample_lognormal_positive():
    big = sample(scope.lognormal(0.0, 1.0, size=(5000,)), RNG())
    assert big.min() > 0
    assert abs(np.log(big).mean()) < 0.1


def test_sample_qlognormal():
    big = sample(scope.qlognormal(2.0, 1.0, 1.0, size=(2000,)), RNG())
    assert np.allclose(np.round(big), big)
    assert big.min() >= 0.0


def test_sample_randint_range():
    big = sample(scope.randint(7, size=(5000,)), RNG())
    assert set(np.unique(big)) <= set(range(7))
    # roughly uniform
    counts = np.bincount(big, minlength=7)
    assert counts.min() > 5000 / 7 * 0.7


def test_sample_categorical_probs():
    p = [0.1, 0.6, 0.3]
    big = sample(scope.categorical(p, size=(5000,)), RNG())
    freq = np.bincount(big, minlength=3) / 5000
    assert np.allclose(freq, p, atol=0.05)


def test_sample_nested_space():
    space = {"a": scope.uniform(0.0, 1.0), "b": [scope.normal(0.0, 1.0), 3]}
    s = sample(space, RNG())
    assert set(s.keys()) == {"a", "b"}
    assert 0 <= s["a"] < 1
    assert s["b"][1] == 3


def test_sample_is_seeded_deterministic():
    space = {"a": scope.uniform(0.0, 1.0), "b": scope.randint(10)}
    s1 = sample(space, np.random.default_rng(7))
    s2 = sample(space, np.random.default_rng(7))
    assert s1 == s2


def test_sample_does_not_mutate_space():
    node = scope.uniform(0.0, 1.0)
    sample(node, RNG())
    # original node must not have acquired an rng kwarg
    assert not any(k == "rng" for k, _ in node.named_args)


def test_stochastic_without_rng_raises():
    node = scope.uniform(0.0, 1.0)
    with pytest.raises(ValueError):
        rec_eval(node)


def test_recursive_set_rng_kwarg_in_place():
    node = scope.normal(0.0, 1.0)
    expr = scope.add(node, as_apply(1.0))
    recursive_set_rng_kwarg(expr, np.random.default_rng(0))
    assert any(k == "rng" for k, _ in node.named_args)
    val = rec_eval(expr)
    assert np.isfinite(val)
