"""Compile-plane observability: the persistent compile ledger, the
ledger-driven AOT warmup behind /readyz, and cold-request containment.

- the ledger's on-disk format is crash-consistent: per-record CRC +
  leading-newline resync (torn tail, real mid-write SIGKILL);
- a ledger written under a different jax/library fingerprint must
  never mark buckets warm (version invalidation);
- a ledger record is self-sufficient for replay: zero-filled arguments
  at the recorded shapes rebuild the EXACT fused program identity, so
  a restarted server can pre-warm with no study state at all;
- the warmup driver replays ledger + predicted (dry-prepare) grids off
  the real dispatch path, /readyz gates on it, and its progress rides
  the 503 body + GET /v1/warmup;
- cold containment serves an unwarmed suggest host-side (tagged
  served_cold) while the compile proceeds off-thread;
- SL607 pages on post-ready cold-compile rate, never on warmup's own
  compiles.
"""

import json
import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

from hyperopt_tpu import compile_ledger, hp
from hyperopt_tpu.algos import rand, tpe, tpe_device
from hyperopt_tpu.base import JOB_STATE_DONE, STATUS_OK, Domain, Trials

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

SPACE = {
    "x": hp.uniform("x", -5, 5),
    "c": hp.choice("c", ["a", "b"]),
}

ALGO_PARAMS = {"n_startup_jobs": 2, "n_EI_candidates": 16}


def _history_trials(n=6, seed=0):
    domain = Domain(lambda cfg: 0.0, SPACE)
    trials = Trials()
    rng = np.random.default_rng(seed)
    for i in range(n):
        (doc,) = rand.suggest([i], domain, trials, i)
        doc["state"] = JOB_STATE_DONE
        doc["result"] = {"status": STATUS_OK, "loss": float(rng.normal())}
        trials._insert_trial_docs([doc])
        trials.refresh()
    return domain, trials


def _prepared_requests(domain, trials, n_cand=16):
    prep = tpe.suggest_prepare(
        [999], domain, trials, 0, n_startup_jobs=2, n_EI_candidates=n_cand
    )
    assert prep is not None
    return prep[0]


# ---------------------------------------------------------------------
# ledger format + crash consistency
# ---------------------------------------------------------------------


class TestLedgerFormat:
    def _record_one(self, ledger, domain=None, trials=None, n_cand=16):
        if domain is None:
            domain, trials = _history_trials()
        requests = _prepared_requests(domain, trials, n_cand=n_cand)
        sig = tpe_device._multi_sig(requests)
        shapes = tpe_device.args_shapes([a for _, a, _ in requests])
        return ledger.record_compile(
            sig, shapes, duration_s=1.25, cache_hit=False, n_requests=1
        )

    def test_roundtrip_latest_per_key(self, tmp_path):
        path = str(tmp_path / "ledger.jsonl")
        ledger = compile_ledger.CompileLedger(path)
        domain, trials = _history_trials()
        rec = self._record_one(ledger, domain, trials)
        # same program again: latest record wins, no duplicate entry
        ledger.record_compile(
            rec["sig"], rec["shapes"], duration_s=0.5, cache_hit=True
        )
        assert len(ledger) == 1
        # a DIFFERENT program (different candidate count -> statics)
        self._record_one(ledger, domain, trials, n_cand=32)
        assert len(ledger) == 2

        loaded = compile_ledger.CompileLedger(path)
        assert len(loaded) == 2
        assert loaded.n_torn_lines == 0
        by_key = {e["replay_key"]: e for e in loaded.entries()}
        assert by_key[rec["replay_key"]]["duration_s"] == 0.5
        assert by_key[rec["replay_key"]]["cache_hit"] is True
        grid = loaded.grid()
        assert all(isinstance(k[0], int) and k[1] for k in grid)

    def test_torn_tail_resync(self, tmp_path):
        path = str(tmp_path / "ledger.jsonl")
        ledger = compile_ledger.CompileLedger(path)
        domain, trials = _history_trials()
        self._record_one(ledger, domain, trials, n_cand=16)
        self._record_one(ledger, domain, trials, n_cand=32)
        with open(path, "r+b") as f:
            f.truncate(os.path.getsize(path) - 11)
        loaded = compile_ledger.CompileLedger(path)
        assert loaded.n_torn_lines == 1
        assert len(loaded) == 1
        # the next append's leading newline re-synchronizes the reader
        self._record_one(loaded, domain, trials, n_cand=64)
        again = compile_ledger.CompileLedger(path)
        assert again.n_torn_lines == 1
        assert len(again) == 2

    def test_survives_midwrite_sigkill(self, tmp_path):
        """A writer SIGKILL'd at a random moment leaves at most one
        torn record; everything before it loads clean."""
        path = str(tmp_path / "ledger.jsonl")
        child = subprocess.Popen(
            [sys.executable, "-c", f"""
import sys; sys.path.insert(0, {REPO!r})
from hyperopt_tpu import compile_ledger
led = compile_ledger.CompileLedger({path!r})
sig = [["cont", [["cap_b", 4], ["k", 1]]]]
i = 0
while True:
    shapes = [[[[i % 7 + 1, 8], "float32"]]]
    led.record_compile(sig, shapes, duration_s=0.1 * i)
    i += 1
"""],
            env=dict(os.environ, JAX_PLATFORMS="cpu"),
        )
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            if os.path.exists(path) and os.path.getsize(path) > 4096:
                break
            time.sleep(0.02)
        child.send_signal(signal.SIGKILL)
        child.wait(timeout=30)
        assert os.path.getsize(path) > 0
        loaded = compile_ledger.CompileLedger(path)
        assert loaded.n_torn_lines <= 1
        assert len(loaded) >= 1
        # the survivors parse into well-formed records
        for e in loaded.entries():
            assert "replay_key" in e and "duration_s" in e

    def test_compaction_keeps_live_entries(self, tmp_path):
        path = str(tmp_path / "ledger.jsonl")
        ledger = compile_ledger.CompileLedger(path)
        sig = [["cont", [["cap_b", 4], ["k", 1]]]]
        shapes = [[[[4, 8], "float32"]]]
        for i in range(3 * compile_ledger.COMPACT_APPEND_FACTOR):
            ledger.record_compile(sig, shapes, duration_s=float(i))
        assert len(ledger) == 1
        raw = open(path, "rb").read()
        records, torn = __import__(
            "hyperopt_tpu.tracing", fromlist=["parse_trace_log"]
        ).parse_trace_log(raw)
        assert torn == 0
        # compacted at least once: far fewer lines than appends
        assert len(records) < 2 * compile_ledger.COMPACT_APPEND_FACTOR
        assert compile_ledger.CompileLedger(path).entries()[0][
            "duration_s"
        ] == float(3 * compile_ledger.COMPACT_APPEND_FACTOR - 1)

    def test_fingerprint_invalidation(self, tmp_path):
        """A stale ledger (older jax / different backend) must not mark
        buckets warm: entries() filters, and the warmup driver skips."""
        path = str(tmp_path / "ledger.jsonl")
        ledger = compile_ledger.CompileLedger(path)
        domain, trials = _history_trials()
        requests = _prepared_requests(domain, trials)
        sig = tpe_device._multi_sig(requests)
        shapes = tpe_device.args_shapes([a for _, a, _ in requests])
        stale_fp = {"version": "0.0.0", "jax": "0.0.1", "backend": "tpu"}
        ledger.record_compile(sig, shapes, duration_s=9.0, fp=stale_fp)
        current = compile_ledger.fingerprint()
        assert ledger.entries() and not ledger.entries(
            current_fingerprint=current
        )
        driver = compile_ledger.WarmupDriver(ledger=ledger)
        assert driver.plan() == []
        driver.start()
        assert driver.wait(30)
        assert driver.progress_brief()["total"] == 0
        # the same record stamped with the CURRENT fingerprint replays
        ledger2 = compile_ledger.CompileLedger(str(tmp_path / "l2.jsonl"))
        ledger2.record_compile(sig, shapes, duration_s=9.0, fp=current)
        driver2 = compile_ledger.WarmupDriver(ledger=ledger2)
        plan = driver2.plan()
        assert len(plan) == 1
        assert plan[0]["source"] == "ledger"


# ---------------------------------------------------------------------
# replay identity + warm-key tracking
# ---------------------------------------------------------------------


class TestReplayIdentity:
    def test_requests_from_record_not_replayable(self):
        assert compile_ledger.requests_from_record({}) is None
        assert compile_ledger.requests_from_record(
            {"sig": [["cont", []]], "shapes": []}
        ) is None
        # a mesh-sharded program never replays from JSON
        rec = {
            "sig": [["cont", [["mesh", "Mesh(dp=4)"], ["k", 1]]]],
            "shapes": [[[[4, 8], "float32"]]],
        }
        assert compile_ledger.requests_from_record(rec) is None

    def test_replay_reproduces_program_identity(self, tmp_path):
        """Zero-filled args at the recorded shapes map to the same
        program key the live dispatch traced — and dispatching the
        replay of a warm program never retraces."""
        path = str(tmp_path / "ledger.jsonl")
        ledger = compile_ledger.CompileLedger(path)
        recorder = compile_ledger.CompileLedgerRecorder(ledger).install()
        try:
            domain, trials = _history_trials()
            requests = _prepared_requests(domain, trials)
            assert not tpe_device.is_warm(requests)
            tpe_device.multi_family_suggest_async(requests)()
            assert tpe_device.is_warm(requests)
            assert len(ledger) == 1
            (rec,) = compile_ledger.CompileLedger(path).entries()
            replay = compile_ledger.requests_from_record(rec)
            assert replay is not None
            assert tpe_device.is_warm(replay)
            n_before = len(ledger)
            tpe_device.multi_family_suggest_async(replay)()
            assert len(ledger) == n_before, "replay of warm program retraced"
            # the recorder stamped the shared attribution key
            assert (rec["bucket"], rec["families"]) in [
                (int(b), f) for (b, f) in ledger.grid()
            ]
            assert rec["duration_s"] > 0
        finally:
            recorder.uninstall()

    def test_reset_device_state_clears_warm_keys(self):
        domain, trials = _history_trials(seed=3)
        requests = _prepared_requests(domain, trials, n_cand=24)
        tpe_device.multi_family_suggest_async(requests)()
        assert tpe_device.is_warm(requests)
        tpe_device.reset_device_state()
        assert not tpe_device.is_warm(requests)

    def test_fused_is_warm_canonical_order(self):
        d1, t1 = _history_trials(seed=11)
        d2, t2 = _history_trials(n=10, seed=12)
        g1 = _prepared_requests(d1, t1, n_cand=48)
        g2 = _prepared_requests(d2, t2, n_cand=48)
        if tpe_device.fused_is_warm([g1, g2]):
            tpe_device.reset_device_state()
            g1 = _prepared_requests(d1, t1, n_cand=48)
            g2 = _prepared_requests(d2, t2, n_cand=48)
        assert not tpe_device.fused_is_warm([g1, g2])
        tpe_device.multi_study_suggest_async([g1, g2])[0]()
        # batch order must not matter — the fused key is canonical
        assert tpe_device.fused_is_warm([g1, g2])
        assert tpe_device.fused_is_warm([g2, g1])


# ---------------------------------------------------------------------
# warmup behind /readyz (service level)
# ---------------------------------------------------------------------


def _service(root, **kwargs):
    from hyperopt_tpu.service import OptimizationService

    kwargs.setdefault("slo_tick", 3600)
    return OptimizationService(root=str(root), **kwargs)


def _drive(svc, sid="s1", n=5, seed=0):
    svc.create_study(
        sid, SPACE, seed=seed, algo="tpe", algo_params=ALGO_PARAMS,
        exist_ok=True,
    )
    for _ in range(n):
        (t,) = svc.suggest(sid)
        svc.report(sid, t["tid"], loss=float(t["vals"]["x"]) ** 2)


class TestWarmupService:
    def test_restart_warms_from_ledger_and_prediction(self, tmp_path):
        svc = _service(tmp_path)
        try:
            _drive(svc, n=5)
            assert len(svc.compile_ledger) >= 1
        finally:
            svc.close()
        # the ledger survived on disk next to the studies
        assert os.path.exists(
            os.path.join(str(tmp_path), "compile_ledger.jsonl")
        )
        svc2 = _service(tmp_path)
        try:
            assert svc2.warmup.wait(120)
            status = svc2.warmup_status()
            assert status["finished"] is True
            assert status["total"] >= 1
            states = {i["state"] for i in status["items"]}
            assert states <= {"warm", "skipped"}
            assert any(i["source"] == "ledger" for i in status["items"])
            r = svc2.readiness()
            assert r["ready"] is True
            assert r["warmup"]["finished"] is True
            assert r["warmup"]["warmed"] == r["warmup"]["total"]
            # post-warmup, the recovered study's next suggest is warm:
            # zero cold suggests after ready
            (t,) = svc2.suggest("s1")
            assert t["tid"] is not None
            stats = svc2.stats.summary()
            assert stats["n_cold_after_ready"] == 0
        finally:
            svc2.close()

    def test_prediction_probe_without_ledger(self, tmp_path):
        """With no ledger at all, the dry-prepare probe per recovered
        study still predicts the grid (the RecompilationAuditor
        inventory path)."""
        tpe_device.reset_device_state()  # force a real compile below
        svc = _service(tmp_path)
        try:
            _drive(svc, n=5)
        finally:
            svc.close()
        ledger_path = os.path.join(str(tmp_path), "compile_ledger.jsonl")
        if os.path.exists(ledger_path):
            os.unlink(ledger_path)
        tpe_device.reset_device_state()
        svc2 = _service(tmp_path)
        try:
            assert svc2.warmup.wait(120)
            status = svc2.warmup_status()
            assert status["total"] >= 1
            assert any(
                i["source"] == "predicted" for i in status["items"]
            )
            assert all(i["state"] == "warm" for i in status["items"])
            # the probe consumed nothing: seed cursor untouched
            study = svc2.registry.get("s1")
            assert study.n_seeds_drawn == study.n_seeds_committed
        finally:
            svc2.close()

    def test_readyz_503_body_reports_warmup_progress(self, tmp_path):
        svc = _service(tmp_path)
        try:
            # hold warmup open artificially: readiness must say 503
            # WITH warmup progress (the wait_ready-actionable body)
            svc.warmup._done.clear()
            r = svc.readiness()
            assert r["ready"] is False
            assert r["warmup"]["finished"] is False
            assert "warmed" in r["warmup"] and "total" in r["warmup"]
            svc.warmup._done.set()
            assert svc.readiness()["ready"] is True
        finally:
            svc.close()

    def test_warmup_over_http_and_client(self, tmp_path):
        from hyperopt_tpu.service import ServiceClient, ServiceServer

        svc = _service(tmp_path)
        server = ServiceServer(svc).start()
        try:
            client = ServiceClient(server.url)
            ready = client.wait_ready(timeout=60)
            assert ready["warmup"]["finished"] is True
            wu = client.warmup()
            assert wu["finished"] is True
            assert isinstance(wu["items"], list)
            assert wu["ledger"] is not None
            text = client.metrics()
            assert "hyperopt_compile_warmup_total" in text
            assert "hyperopt_compile_warmup_finished 1" in text
            assert "hyperopt_compile_cache_hits_total" in text
        finally:
            server.stop()

    def test_warmup_disabled(self, tmp_path):
        svc = _service(tmp_path, warmup=False)
        try:
            assert svc.warmup.finished
            assert svc.readiness()["ready"] is True
            assert svc.readiness()["warmup"]["enabled"] is False
        finally:
            svc.close()


# ---------------------------------------------------------------------
# cold containment
# ---------------------------------------------------------------------


class TestColdContainment:
    def test_cold_fallback_serves_host_side_then_warms(self, tmp_path):
        tpe_device.reset_device_state()
        svc = _service(tmp_path, cold_fallback=True)
        try:
            svc.create_study(
                "cold", SPACE, seed=0, algo="tpe",
                algo_params=ALGO_PARAMS,
            )
            deadline = time.monotonic() + 120
            while time.monotonic() < deadline:
                (t,) = svc.suggest("cold")
                svc.report(
                    "cold", t["tid"], loss=float(t["vals"]["x"]) ** 2
                )
                stats = svc.stats.summary()
                if stats["n_dispatches"] >= 1:
                    break
                # give the background compile thread a beat
                time.sleep(0.05)
            stats = svc.stats.summary()
            # the first device-plane suggest hit an unwarmed program:
            # served from the host-side fallback, compile off-thread
            assert stats["n_cold_fallbacks"] >= 1
            # the background compile landed and later suggests went
            # through the device plane (fused dispatches happened)
            assert stats["n_dispatches"] >= 1
            # containment kept compiles out of the request path
            # entirely: background compiles are excluded from cold
            # attribution (tpe_device.background_compiles), so no
            # request is ever tagged cold — not even one overlapping
            # an off-thread compile event
            assert stats["n_cold_suggests"] == 0
            assert stats["phase_seconds"].get("cold_fallback")
        finally:
            svc.close()

    def test_cold_fallback_off_keeps_exact_trajectory(self, tmp_path):
        """Default (containment off): the served trajectory equals the
        serial fmin trajectory — the determinism contract is intact."""
        from hyperopt_tpu.fmin import fmin

        svc = _service(tmp_path / "svc", cold_fallback=False)
        try:
            svc.create_study(
                "det", SPACE, seed=7, algo="tpe", algo_params=ALGO_PARAMS
            )
            got = []
            for _ in range(6):
                (t,) = svc.suggest("det")
                svc.report(
                    "det", t["tid"], loss=float(t["vals"]["x"]) ** 2
                )
                got.append(t["vals"]["x"])
        finally:
            svc.close()
        trials = Trials()
        fmin(
            lambda cfg: cfg["x"] ** 2, SPACE,
            algo=__import__(
                "functools"
            ).partial(tpe.suggest, **ALGO_PARAMS),
            max_evals=6, trials=trials, rstate=np.random.default_rng(7),
        )
        ref = [v[0] for v in (
            d["misc"]["vals"]["x"] for d in trials._dynamic_trials
        )]
        np.testing.assert_allclose(got, ref)


# ---------------------------------------------------------------------
# SL607
# ---------------------------------------------------------------------


class _Clock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


class TestSL607:
    def _engine(self, ss, clock):
        from hyperopt_tpu import slo

        return slo.SloEngine(
            service_stats=ss, time_fn=clock, min_window_s=0.0,
            snapshot_interval=1.0,
        )

    def test_cold_before_ready_never_counts(self):
        from hyperopt_tpu.observability import ServiceStats

        ss = ServiceStats()
        clock = _Clock()
        eng = self._engine(ss, clock)
        for _ in range(40):
            ss.record_request("suggest", seconds=0.01, cold=True)
        clock.t = 100.0
        rows = {r["rule"]: r for r in eng.evaluate(force=True)}
        assert rows["SL607"]["status"] == "ok"
        assert rows["SL607"]["value"] == 0.0

    def test_post_ready_cold_rate_breaches(self):
        from hyperopt_tpu.observability import ServiceStats

        ss = ServiceStats()
        clock = _Clock()
        eng = self._engine(ss, clock)
        ss.mark_ready()
        for i in range(40):
            ss.record_request("suggest", seconds=0.01, cold=(i % 2 == 0))
        clock.t = 100.0
        rows = {r["rule"]: r for r in eng.evaluate(force=True)}
        assert rows["SL607"]["status"] == "breach"
        assert rows["SL607"]["value"] == pytest.approx(0.5)

    def test_quiet_window_with_cold_suggest_still_counts(self):
        from hyperopt_tpu.observability import ServiceStats

        ss = ServiceStats()
        clock = _Clock()
        eng = self._engine(ss, clock)
        ss.mark_ready()
        for _ in range(3):
            ss.record_request("suggest", seconds=0.01, cold=True)
        clock.t = 100.0
        rows = {r["rule"]: r for r in eng.evaluate(force=True)}
        # 3 cold / floor-of-20 = 15% >> 1% budget: a compile storm in a
        # quiet window must not hide behind the traffic floor
        assert rows["SL607"]["status"] == "breach"

    def test_no_traffic_is_no_data(self):
        from hyperopt_tpu.observability import ServiceStats

        ss = ServiceStats()
        clock = _Clock()
        eng = self._engine(ss, clock)
        clock.t = 100.0
        rows = {r["rule"]: r for r in eng.evaluate(force=True)}
        assert rows["SL607"]["status"] == "no_data"


# ---------------------------------------------------------------------
# race-lint registration
# ---------------------------------------------------------------------


def test_compile_ledger_registered_for_race_lint():
    from hyperopt_tpu.analysis import discover_race_files, lint_file

    path = os.path.join(REPO, "hyperopt_tpu", "compile_ledger.py")
    assert path in discover_race_files()
    assert lint_file(path) == []
