"""Tests of the space compiler (reference parity: test_vectorize.py).

Checks compiled-vs-interpreted distribution agreement, activity masks under
conditionality, determinism, and the idxs/vals sparse data model.
"""

import numpy as np
import pytest

from hyperopt_tpu import hp
from hyperopt_tpu.pyll import as_apply, scope
from hyperopt_tpu.vectorize import CompiledSpace, idxs_vals_from_batch


def test_compiles_flat_space():
    space = {
        "lr": hp.loguniform("lr", np.log(1e-5), np.log(1e-1)),
        "n": hp.randint("n", 8),
        "m": hp.quniform("m", 0, 100, 10),
    }
    cs = CompiledSpace(space)
    assert cs.compiled
    assert set(cs.labels) == {"lr", "n", "m"}
    vals, active = cs.sample_batch(0, 500)
    assert vals["lr"].shape == (500,)
    assert np.all((vals["lr"] >= 1e-5) & (vals["lr"] <= 1e-1 + 1e-6))
    assert np.all((vals["n"] >= 0) & (vals["n"] < 8))
    assert np.allclose(np.round(vals["m"] / 10) * 10, vals["m"])
    assert all(active[lb].all() for lb in cs.labels)


def test_all_dists_compile_and_sample():
    space = {
        "u": hp.uniform("u", -1, 1),
        "qu": hp.quniform("qu", 0, 10, 0.5),
        "ui": hp.uniformint("ui", 0, 5),
        "lu": hp.loguniform("lu", 0, 2),
        "qlu": hp.qloguniform("qlu", 0, 3, 1),
        "n": hp.normal("n", 3, 2),
        "qn": hp.qnormal("qn", 0, 2, 1),
        "ln": hp.lognormal("ln", 0, 1),
        "qln": hp.qlognormal("qln", 0, 1, 1),
        "ri": hp.randint("ri", 2, 9),
        "c": hp.pchoice("c", [(0.2, "a"), (0.8, "b")]),
    }
    cs = CompiledSpace(space)
    assert cs.compiled, cs.compile_error
    vals, active = cs.sample_batch(1, 1000)
    assert np.all((vals["u"] >= -1) & (vals["u"] < 1))
    assert np.all(vals["ui"] >= 0) and np.all(vals["ui"] <= 5)
    assert np.all(vals["ri"] >= 2) and np.all(vals["ri"] < 9)
    assert np.all((vals["c"] == 0) | (vals["c"] == 1))
    assert abs(np.mean(vals["c"]) - 0.8) < 0.05
    assert np.all(vals["ln"] > 0)
    assert np.allclose(np.round(vals["qn"]), vals["qn"])


def test_determinism_same_seed():
    space = {"x": hp.uniform("x", 0, 1), "k": hp.randint("k", 5)}
    cs = CompiledSpace(space)
    v1, _ = cs.sample_batch(42, 10)
    v2, _ = cs.sample_batch(42, 10)
    assert np.array_equal(v1["x"], v2["x"])
    assert np.array_equal(v1["k"], v2["k"])
    v3, _ = cs.sample_batch(43, 10)
    assert not np.array_equal(v1["x"], v3["x"])


def test_conditional_activity_masks():
    space = hp.choice(
        "model",
        [
            {"kind": "svm", "C": hp.loguniform("C", -3, 3)},
            {"kind": "rf", "depth": hp.randint("depth", 10)},
        ],
    )
    cs = CompiledSpace(space)
    assert cs.compiled
    vals, active = cs.sample_batch(7, 2000)
    choice = vals["model"]
    assert np.array_equal(active["C"], choice == 0)
    assert np.array_equal(active["depth"], choice == 1)
    assert active["model"].all()
    # both branches exercised
    assert 0.3 < choice.mean() < 0.7


def test_nested_conditional_activity():
    inner = hp.choice("inner", [{"a": hp.uniform("a", 0, 1)}, {"b": hp.uniform("b", 0, 1)}])
    space = hp.choice("outer", [inner, {"c": hp.uniform("c", 0, 1)}])
    cs = CompiledSpace(space)
    vals, active = cs.sample_batch(3, 2000)
    outer, inner_v = vals["outer"], vals["inner"]
    np.testing.assert_array_equal(active["a"], (outer == 0) & (inner_v == 0))
    np.testing.assert_array_equal(active["b"], (outer == 0) & (inner_v == 1))
    np.testing.assert_array_equal(active["c"], outer == 1)
    np.testing.assert_array_equal(active["inner"], outer == 0)


def test_compiled_matches_interpreted_statistically():
    """Same distributions through the jitted path and the rec_eval path."""
    space = {
        "n": hp.normal("n", 2.0, 3.0),
        "lu": hp.loguniform("lu", np.log(0.1), np.log(10.0)),
    }
    cs = CompiledSpace(space)
    assert cs.compiled
    cvals, _ = cs.sample_batch(0, 8000)
    # force the interpreted path on a copy
    cs2 = CompiledSpace(space)
    ivals, _ = cs2._sample_interpreted(0, 2000)
    assert abs(cvals["n"].mean() - ivals["n"].mean()) < 0.25
    assert abs(cvals["n"].std() - ivals["n"].std()) < 0.25
    assert abs(np.log(cvals["lu"]).mean() - np.log(ivals["lu"]).mean()) < 0.15


def test_uncompilable_space_falls_back():
    # non-literal distribution parameter -> interpreted path
    high = as_apply(1.0) + 1.0
    space = {"x": scope.float(scope.hyperopt_param("x", scope.uniform(0.0, high)))}
    cs = CompiledSpace(space)
    assert not cs.compiled
    vals, active = cs.sample_batch(0, 50)
    assert np.all((vals["x"] >= 0) & (vals["x"] < 2.0))
    assert active["x"].all()


def test_interpreted_fallback_conditionals():
    high = as_apply(1.0) + 0.0  # defeat compilation
    space = hp.choice(
        "c",
        [
            {"x": scope.float(scope.hyperopt_param("x", scope.uniform(0.0, high)))},
            {"y": hp.uniform("y", 0, 1)},
        ],
    )
    cs = CompiledSpace(space)
    assert not cs.compiled
    vals, active = cs.sample_batch(0, 100)
    # activity from lazy evaluation: exactly one branch active per draw
    assert np.array_equal(active["x"], ~active["y"])


def test_idxs_vals_from_batch():
    space = hp.choice("c", [{"x": hp.uniform("x", 0, 1)}, {"k": hp.randint("k", 3)}])
    cs = CompiledSpace(space)
    vals, active = cs.sample_batch(0, 6)
    tids = [10, 11, 12, 13, 14, 15]
    idxs, vv = idxs_vals_from_batch(tids, vals, active, cs.specs)
    assert idxs["c"] == tids
    assert len(idxs["x"]) + len(idxs["k"]) == 6
    for t, v in zip(idxs["x"], vv["x"]):
        assert isinstance(t, int) and isinstance(v, float)
    for t, v in zip(idxs["k"], vv["k"]):
        assert isinstance(v, int)


def test_param_spec_upper():
    space = {
        "r": hp.randint("r", 3, 9),
        "c": hp.pchoice("c", [(0.5, 0), (0.5, 1)]),
        "u": hp.uniform("u", 0, 1),
    }
    cs = CompiledSpace(space)
    assert cs.specs["r"].upper == 6
    assert cs.specs["c"].upper == 2
    assert cs.specs["u"].upper is None


def test_device_sample_batch_returns_jax_arrays():
    import jax.numpy as jnp
    import jax

    space = {"x": hp.uniform("x", 0, 1)}
    cs = CompiledSpace(space)
    vals, active = cs.device_sample_batch(jax.random.PRNGKey(0), 16)
    assert isinstance(vals["x"], jnp.ndarray)
    assert vals["x"].shape == (16,)
