"""Closed-loop control plane (hyperopt_tpu.control).

Covers the PR 19 contract:

- KnobSet: typed validation envelope, all-or-nothing batch writes,
  out-of-envelope static values (constructor ground truth) with a
  revert that never re-range-checks, provenance ring + CRC-framed
  journal that survives a torn tail;
- guardrail bounds derived from the SL6xx catalog and the proposal
  clamp;
- ObjectiveProbe: one-window delta scoring, compile/chaos
  contamination discards, insufficient-traffic discards, and the
  loss formula;
- Controller state machine: evaluated / discarded / held /
  breach-revert / frozen with exponential re-arm / exception-revert;
  every decision flight-recorded, journaled, and traced;
- durability: a killed controller restarts and resumes its OWN Trials
  exactly (same proposal sequence as an uninterrupted run), stranded
  mid-window proposals repaired to failed trials;
- ``control_enabled=False`` (the default) is machine-checked inert:
  the fixed-seed service trajectory is trial-for-trial identical to
  serial ``fmin`` and the KnobSet never moves;
- SH5xx actuation: per-study ``early_stop`` opt-in stops a stalled
  study, releases its admission slot, counts the reclaim, and is
  reversible via resume — surviving a restart via the config blob;
- the HTTP plane: GET/POST ``/v1/config`` (validation 400, loopback
  403), the 409 ``StudyStopped`` mapping, and the resume route.
"""

import itertools
import json
import os
import threading
import time
from functools import partial

import numpy as np
import pytest

from hyperopt_tpu import Trials, fmin, hp
from hyperopt_tpu import slo as slo_mod
from hyperopt_tpu import tracing
from hyperopt_tpu.algos import tpe
from hyperopt_tpu.base import JOB_STATE_DONE, JOB_STATE_ERROR
from hyperopt_tpu.control import (
    STOP_RULES,
    Controller,
    ControlStats,
    KnobSet,
    KnobSpec,
    ObjectiveProbe,
    WindowResult,
    build_stop_fn,
    guardrail_bounds,
)
from hyperopt_tpu.fmin import space_eval
from hyperopt_tpu.observability import FaultStats, ServiceStats
from hyperopt_tpu.service import (
    BackpressureError,
    OptimizationService,
    ServiceClient,
    ServiceClientError,
    ServiceServer,
    StudyExists,
    StudyStopped,
)

SPACE = {
    "x": hp.uniform("x", -5, 5),
    "c": hp.choice("c", ["a", "b"]),
    "w": hp.quniform("w", 0, 10, 1),
}
AP = {"n_startup_jobs": 4, "n_EI_candidates": 32}


def _objective(cfg):
    return (
        (cfg["x"] - 1.0) ** 2
        + (0.5 if cfg["c"] == "b" else 0.0)
        + 0.1 * cfg["w"]
    )


def _drive(svc, study_id, n, objective=_objective):
    out = []
    for _ in range(n):
        (t,) = svc.suggest(study_id, n=1)
        out.append(t)
        point = space_eval(SPACE, t["vals"])
        svc.report(study_id, t["tid"], loss=objective(point))
    return out


def _serial_fmin_vals(seed, max_evals, ap=AP):
    trials = Trials()
    fmin(
        _objective, SPACE, algo=partial(tpe.suggest, **ap),
        max_evals=max_evals, trials=trials,
        rstate=np.random.default_rng(seed), show_progressbar=False,
        verbose=False, max_speculation=0,
    )
    return [
        {k: v[0] for k, v in t["misc"]["vals"].items() if len(v)}
        for t in trials.trials
    ]


def _mk_knobs(**overrides):
    static = {
        "batch_window": 0.004, "max_batch": 8,
        "max_queue": 1024, "max_speculation": 0,
    }
    static.update(overrides)
    return KnobSet(static=static)


class _ScoreProbe:
    """Deterministic probe: the loss is a pure function of the applied
    knob point, so two controllers walking the same proposal sequence
    observe identical losses (the restart-resume proof needs this)."""

    def __init__(self, knobs):
        self.knobs = knobs

    def open(self):
        return {"t": 0.0}

    def close(self, opened):
        v = self.knobs.values()
        loss = (
            v["batch_window"] * 10.0
            + v["max_batch"] * 1e-3
            + v["max_speculation"] * 1e-4
        )
        return WindowResult(
            ok=True, loss=loss, warm_p99_s=loss,
            mean_queue_depth=0.0, duty_cycle=None,
            warm_count=9, wall_s=0.01,
        )


class _FixedProbe:
    def __init__(self, result):
        self.result = result

    def open(self):
        return {"t": 0.0}

    def close(self, opened):
        if isinstance(self.result, Exception):
            raise self.result
        return self.result


# ---------------------------------------------------------------------
# knobs
# ---------------------------------------------------------------------


class TestKnobSet:
    def test_spec_coerce_is_type_only(self):
        spec = KnobSpec("k", int, 1, 8)
        assert spec.coerce(3.0) == 3
        assert spec.coerce(100) == 100  # out of range, coerce allows
        with pytest.raises(ValueError):
            spec.coerce(3.7)  # silent truncation refused
        with pytest.raises(ValueError):
            spec.coerce("nope")
        with pytest.raises(ValueError):
            spec.validate(100)  # validate DOES range-check

    def test_set_many_is_all_or_nothing(self):
        ks = _mk_knobs()
        ks.set_many({"batch_window": 0.01}, source="test")
        assert ks.get("batch_window") == 0.01
        assert ks.n_changes == 1
        before = ks.values()
        with pytest.raises(ValueError):
            # max_batch=0 is invalid; batch_window=0.02 must NOT land
            ks.set_many(
                {"batch_window": 0.02, "max_batch": 0}, source="test"
            )
        assert ks.values() == before
        assert ks.n_changes == 1

    def test_unknown_knob_rejected(self):
        with pytest.raises(ValueError):
            KnobSet(static={"warp_factor": 9})
        ks = _mk_knobs()
        with pytest.raises(ValueError):
            ks.set_many({"warp_factor": 9}, source="test")

    def test_out_of_envelope_static_is_ground_truth(self):
        # max_queue=0 (admission off) is below the runtime-write floor
        # of 1 — legal as a constructor value, restorable by revert
        ks = _mk_knobs(max_queue=0)
        assert ks.get("max_queue") == 0
        ks.set_many({"max_queue": 5}, source="test")
        assert not ks.is_static
        ks.revert(source="test")
        assert ks.get("max_queue") == 0
        assert ks.is_static

    def test_provenance_and_journal_survive_torn_tail(self, tmp_path):
        path = str(tmp_path / "ctl" / "knobs.jsonl")
        ks = KnobSet(static={"batch_window": 0.004}, journal_path=path)
        ks.set_many({"batch_window": 0.01}, source="api:127.0.0.1")
        ks.set_many({"max_batch": 16}, source="controller")
        ks.revert(source="controller:revert")
        prov = ks.provenance()
        assert [r["source"] for r in prov] == [
            "api:127.0.0.1", "controller", "controller:revert",
        ]
        assert prov[0]["before"] == {"batch_window": 0.004}
        assert prov[0]["changes"] == {"batch_window": 0.01}
        assert not prov[0]["noop"]
        records = ks.journal_records()
        assert len(records) == 3
        assert records[-1]["values"]["batch_window"] == 0.004
        # a mid-append kill tears the final record: CRC framing means
        # the reader skips it instead of exploding or misparsing
        with open(path, "ab") as f:
            f.write(b"\ndeadbeef {\"torn\": tru")
        assert len(ks.journal_records()) == 3

    def test_clamp_pulls_into_bounds_without_applying(self):
        ks = _mk_knobs()
        out = ks.clamp({"batch_window": 9.0, "max_batch": -3})
        assert out == {"batch_window": 0.5, "max_batch": 1}
        out = ks.clamp(
            {"batch_window": 0.4}, bounds={"batch_window": (0.0, 0.1)}
        )
        assert out == {"batch_window": 0.1}
        assert ks.is_static  # clamp never mutates

    def test_guardrail_bounds_from_sl602(self):
        rules = slo_mod.default_rules(
            latency_absolute={"p99_bound_s": 2.0}
        )
        bounds = guardrail_bounds(rules)
        lo, hi = bounds["batch_window"]
        assert lo == 0.0
        # the ceiling is a small fraction of the p99 bound — the
        # controller must never propose the breach itself
        assert hi == pytest.approx(0.1)
        assert guardrail_bounds([]) == {}


# ---------------------------------------------------------------------
# objective
# ---------------------------------------------------------------------


def _warm(stats, seconds, n=1):
    for _ in range(n):
        stats.record_request("suggest", seconds=seconds, study="s")


class TestObjectiveProbe:
    def test_insufficient_traffic_discarded(self):
        stats = ServiceStats()
        probe = ObjectiveProbe(stats, min_warm=5)
        opened = probe.open()
        _warm(stats, 0.01, n=2)
        result = probe.close(opened)
        assert not result.ok
        assert result.reason == "insufficient_traffic"
        assert result.warm_count == 2

    def test_request_path_compile_contaminates(self):
        stats = ServiceStats()
        probe = ObjectiveProbe(stats, min_warm=5)
        opened = probe.open()
        _warm(stats, 0.01, n=10)
        stats.record_compile(4, "x:uniform")
        result = probe.close(opened)
        assert not result.ok
        assert result.reason == "contaminated:compile"

    def test_background_compile_does_not_contaminate(self):
        stats = ServiceStats()
        probe = ObjectiveProbe(stats, min_warm=5)
        opened = probe.open()
        _warm(stats, 0.01, n=10)
        stats.record_compile(4, "x:uniform", background=True)
        assert probe.close(opened).ok

    def test_chaos_injection_contaminates(self):
        stats = ServiceStats()
        faults = FaultStats()
        probe = ObjectiveProbe(stats, fault_stats=faults, min_warm=5)
        opened = probe.open()
        _warm(stats, 0.01, n=10)
        faults.record("chaos_suggest")
        result = probe.close(opened)
        assert not result.ok
        assert result.reason == "contaminated:chaos"

    def test_loss_formula_and_delta_isolation(self):
        stats = ServiceStats()
        # pre-window pathology must NOT leak into the window's score:
        # the probe deltas against the open snapshot, never lifetime
        _warm(stats, 5.0, n=20)
        stats.record_compile(4, "x:uniform")
        probe = ObjectiveProbe(stats, min_warm=5, queue_weight=0.010)
        opened = probe.open()
        _warm(stats, 0.01, n=12)
        stats.set_queue_depth(4)
        stats.set_queue_depth(2)
        result = probe.close(opened)
        assert result.ok
        assert result.warm_count == 12
        assert result.mean_queue_depth == pytest.approx(3.0)
        # in-window p99 reflects the 10ms burst, not the 5s history
        assert result.warm_p99_s < 1.0
        assert result.duty_cycle is None  # no device stats wired
        assert result.loss == pytest.approx(
            result.warm_p99_s + 0.010 * result.mean_queue_depth
        )


# ---------------------------------------------------------------------
# controller state machine
# ---------------------------------------------------------------------


class TestController:
    def test_evaluated_cycle_applies_within_bounds(self):
        knobs = _mk_knobs()
        stats = ControlStats()
        ctl = Controller(
            knobs, _ScoreProbe(knobs), seed=0, window_s=0.0,
            stats=stats,
        )
        assert ctl.step() == "evaluated"
        assert not knobs.is_static
        values = knobs.values()
        for name in ctl.tuned:
            lo, hi = ctl.bounds[name]
            assert lo <= values[name] <= hi, (name, values[name])
        # untuned knobs are never touched by the controller
        assert values["max_queue"] == 1024
        docs = ctl.trials._dynamic_trials
        assert len(docs) == 1 and docs[0]["state"] == JOB_STATE_DONE
        actions = [d["action"] for d in ctl.recent_decisions()]
        assert actions == ["proposed", "applied", "evaluated"]
        decisions = stats.control_metrics()["decisions"]
        assert decisions == {"proposed": 1, "applied": 1, "evaluated": 1}
        assert stats.control_metrics()["objective"] is not None

    def test_discarded_window_lands_failed_trial(self):
        knobs = _mk_knobs()
        ctl = Controller(
            knobs,
            _FixedProbe(
                WindowResult(False, reason="insufficient_traffic")
            ),
            seed=0, window_s=0.0,
        )
        assert ctl.step() == "discarded"
        docs = ctl.trials._dynamic_trials
        assert len(docs) == 1 and docs[0]["state"] == JOB_STATE_ERROR
        assert not ctl.frozen
        last = ctl.recent_decisions()[-1]
        assert last["action"] == "discarded"
        assert last["reason"] == "insufficient_traffic"

    def test_active_breach_holds_without_actuating(self):
        knobs = _mk_knobs()
        ctl = Controller(
            knobs, _ScoreProbe(knobs), seed=0, window_s=0.0,
            breach_fn=lambda: {
                "transitions": 3, "breaching": ["SL602"],
            },
        )
        assert ctl.step() == "held"
        assert knobs.is_static  # never tune INTO an incident
        last = ctl.recent_decisions()[-1]
        assert last["reason"] == "active_breach"
        assert last["fired_rules"] == ["SL602"]

    def test_breach_transition_reverts_within_one_window(self):
        knobs = _mk_knobs()
        stats = ControlStats()
        schedule = iter([0, 0, 0, 1])
        ctl = Controller(
            knobs, _ScoreProbe(knobs), seed=0, window_s=0.0,
            stats=stats,
            breach_fn=lambda: {
                "transitions": next(schedule, 1), "breaching": [],
            },
        )
        assert ctl.step() == "evaluated"
        assert not knobs.is_static
        assert ctl.step() == "reverted"
        assert knobs.is_static  # static config restored
        assert ctl.frozen
        assert ctl.rearm_in_s() > 0
        assert ctl.step() == "frozen"  # no actuation while frozen
        status = ctl.status()
        assert status["frozen"] and status["freezes_total"] == 1
        assert stats.control_metrics()["frozen"] == 1
        # the breached window's trial is a failed trial, not a loss
        assert status["n_discarded"] == 1

    def test_exception_reverts_and_freezes(self):
        knobs = _mk_knobs()
        ctl = Controller(
            knobs, _FixedProbe(RuntimeError("probe exploded")),
            seed=0, window_s=0.0,
        )
        assert ctl.step() == "reverted"
        assert knobs.is_static and ctl.frozen
        last = ctl.recent_decisions()[-1]
        assert last["action"] == "reverted"
        assert last["reason"] == "exception:RuntimeError"

    def test_exponential_rearm_doubles_per_freeze(self):
        clock = {"t": 0.0}
        calls = itertools.count()
        knobs = _mk_knobs()
        ctl = Controller(
            knobs, _ScoreProbe(knobs), seed=0, window_s=0.0,
            freeze_base_s=10.0, freeze_max_s=100.0,
            time_fn=lambda: clock["t"],
            # transitions grow on every consultation: every completed
            # window sees a transition and trips
            breach_fn=lambda: {
                "transitions": next(calls), "breaching": [],
            },
        )
        assert ctl.step() == "reverted"
        assert ctl.rearm_in_s() == pytest.approx(10.0)
        assert ctl.step() == "frozen"  # still inside the backoff
        clock["t"] = 11.0
        assert ctl.step() == "reverted"  # re-armed, trips again
        assert ctl.rearm_in_s() == pytest.approx(20.0)
        clock["t"] = 32.0
        assert ctl.step() == "reverted"
        assert ctl.rearm_in_s() == pytest.approx(40.0)
        assert ctl.status()["freezes_total"] == 3

    def test_decision_span_emitted_when_traced(self, tmp_path):
        trace_path = str(tmp_path / "trace.jsonl")
        tracer = tracing.Tracer(path=trace_path, sample=1.0)
        knobs = _mk_knobs()
        ctl = Controller(
            knobs, _ScoreProbe(knobs), seed=0, window_s=0.0,
            tracer=tracer,
        )
        assert ctl.step() == "evaluated"
        with open(trace_path, "rb") as f:
            records, torn = tracing.parse_trace_log(f.read())
        assert torn == 0 and records
        blob = json.dumps(records)
        assert "control.decision" in blob
        # the applied decision's span carries the knob point
        assert '"action": "applied"' in blob


# ---------------------------------------------------------------------
# controller durability: restart resumes the Trials exactly
# ---------------------------------------------------------------------


class TestControllerDurability:
    def _applied_sequence(self, ctl):
        return [
            (d["knobs"], d.get("loss"))
            for d in ctl.decision_log_records()
            if d["action"] in ("applied", "evaluated")
        ]

    def test_restart_resumes_proposal_sequence_exactly(self, tmp_path):
        # uninterrupted reference: 5 cycles in one controller life
        ref_knobs = _mk_knobs()
        ref = Controller(
            ref_knobs, _ScoreProbe(ref_knobs), seed=11, window_s=0.0,
            trials_dir=str(tmp_path / "ref"),
        )
        for _ in range(5):
            assert ref.step() == "evaluated"

        # interrupted run: 3 cycles, then the process "dies" (no
        # close, no flush beyond the durable writes) and a NEW
        # controller restarts on the same trials_dir
        k1 = _mk_knobs()
        first = Controller(
            k1, _ScoreProbe(k1), seed=11, window_s=0.0,
            trials_dir=str(tmp_path / "run"),
        )
        for _ in range(3):
            assert first.step() == "evaluated"
        del first

        k2 = _mk_knobs()
        resumed = Controller(
            k2, _ScoreProbe(k2), seed=11, window_s=0.0,
            trials_dir=str(tmp_path / "run"),
        )
        # the resume fast-forwarded the seed cursor past the evidenced
        # draws, and the prior trials are all loaded
        assert resumed.n_draws == 3
        assert resumed.status()["n_trials"] == 3
        assert resumed.status()["n_evaluated"] == 3
        for _ in range(2):
            assert resumed.step() == "evaluated"

        got = self._applied_sequence(resumed)
        want = self._applied_sequence(ref)[-len(got):]
        # the resumed controller's continuation (cycles 4-5) equals
        # the uninterrupted run's cycles 4-5, point for point
        assert [g[0] for g in got[-4:]] == [w[0] for w in want[-4:]]
        for (gk, gl), (wk, wl) in zip(got[-4:], want[-4:]):
            if gl is not None or wl is not None:
                assert gl == pytest.approx(wl)

    def test_stranded_mid_window_proposal_repaired(self, tmp_path):
        knobs = _mk_knobs()
        ctl = Controller(
            knobs, _ScoreProbe(knobs), seed=3, window_s=0.0,
            trials_dir=str(tmp_path / "t"),
        )
        doc, _point = ctl.propose()  # kill -9 lands mid-window here
        del ctl

        knobs2 = _mk_knobs()
        resumed = Controller(
            knobs2, _ScoreProbe(knobs2), seed=3, window_s=0.0,
            trials_dir=str(tmp_path / "t"),
        )
        docs = resumed.trials._dynamic_trials
        assert len(docs) == 1
        assert docs[0]["state"] == JOB_STATE_ERROR
        assert docs[0]["result"]["reason"] == "interrupted"
        # the stranded proposal consumed a draw; the cursor skips it
        assert resumed.n_draws == 1

    def test_decision_journal_is_crc_framed(self, tmp_path):
        knobs = _mk_knobs()
        ctl = Controller(
            knobs, _ScoreProbe(knobs), seed=0, window_s=0.0,
            trials_dir=str(tmp_path / "t"),
        )
        ctl.step()
        records = ctl.decision_log_records()
        assert [r["action"] for r in records] == [
            "proposed", "applied", "evaluated",
        ]
        assert [r["seq"] for r in records] == [1, 2, 3]
        with open(ctl.decisions_log_path, "ab") as f:
            f.write(b"\n0bad0bad {\"torn")
        assert len(ctl.decision_log_records()) == 3


# ---------------------------------------------------------------------
# service integration: inertness, /v1/config core, controller wiring
# ---------------------------------------------------------------------


class TestServiceControl:
    def test_control_off_is_provably_inert(self):
        """The acceptance gate: with the default
        ``control_enabled=False``, the fixed-seed service trajectory
        is trial-for-trial identical to serial ``fmin`` AND the knob
        table never moves — the control plane's existence costs
        nothing until it is asked for."""
        ref = _serial_fmin_vals(seed=42, max_evals=12)
        svc = OptimizationService(root=None, batch_window=0.001)
        try:
            assert svc.controller is None
            assert svc.knobs.is_static
            assert svc.knobs.values()["batch_window"] == 0.001
            svc.create_study("s", SPACE, seed=42, algo="tpe",
                             algo_params=AP)
            got = _drive(svc, "s", 12)
            # after a full campaign: zero knob mutations, zero
            # control decisions, no controller thread
            assert svc.knobs.n_changes == 0
            assert svc.knobs.is_static
            assert svc.controller is None
            status = svc.service_status()
            assert status["control"]["enabled"] is False
            assert status["control"]["controller"] is None
        finally:
            svc.close()
        assert len(ref) == len(got) == 12
        for i, (rv, g) in enumerate(zip(ref, got)):
            assert rv.keys() == g["vals"].keys(), (i, rv, g)
            for k in rv:
                assert np.isclose(rv[k], g["vals"][k]), (i, k, rv, g)

    def test_get_set_config_core(self):
        svc = OptimizationService(root=None, batch_window=0.004)
        try:
            cfg = svc.get_config()
            assert cfg["control_enabled"] is False
            assert cfg["knobs"]["batch_window"]["value"] == 0.004
            out = svc.set_config(
                {"knobs": {"batch_window": 0.002, "max_batch": 16}},
                source="test",
            )
            assert out["values"]["batch_window"] == 0.002
            assert out["is_static"] is False
            with pytest.raises(ValueError):
                svc.set_config({"knobs": {"max_batch": 0}})
            with pytest.raises(ValueError):
                svc.set_config({})  # neither knobs nor revert
            out = svc.set_config({"revert": True}, source="test")
            assert out["is_static"] is True
            assert out["values"]["batch_window"] == 0.004
            provenance = svc.get_config()["provenance"]
            assert [p["source"] for p in provenance][:1] == ["test"]
        finally:
            svc.close()

    def test_scheduler_reads_knobs_per_batch(self):
        # a runtime knob write lands on the NEXT batch, no restart:
        # the scheduler's view IS the KnobSet
        svc = OptimizationService(root=None, batch_window=0.001)
        try:
            svc.create_study("s", SPACE, seed=0, algo_params=AP)
            _drive(svc, "s", 2)
            svc.set_config({"knobs": {"max_batch": 2}}, source="test")
            _drive(svc, "s", 2)
            assert svc.knobs.get("max_batch") == 2
            assert not svc.knobs.is_static
        finally:
            svc.close()

    def test_self_tune_attaches_a_live_controller(self, tmp_path):
        svc = OptimizationService(
            root=str(tmp_path / "r"), batch_window=0.001,
            control_enabled=True, control_window_s=0.05,
            control_interval_s=0.0, control_seed=7,
        )
        try:
            assert svc.controller is not None
            assert svc.controller.durable
            assert svc.controller.seed == 7
            svc.create_study("s", SPACE, seed=0, algo_params=AP)
            deadline = time.monotonic() + 20.0
            while time.monotonic() < deadline:
                _drive(svc, "s", 2)
                if svc.controller.status()["n_decisions"] >= 3:
                    break
            status = svc.service_status()["control"]
            assert status["enabled"] is True
            assert status["controller"]["n_decisions"] >= 3
            metrics = svc.metrics_text()
            assert "hyperopt_control_decisions_total" in metrics
            # every decision is also in the durable journal
            journaled = svc.controller.decision_log_records()
            assert len(journaled) == len(
                svc.controller.recent_decisions()
            )
        finally:
            svc.close()


# ---------------------------------------------------------------------
# SH5xx actuation: early-stop opt-in, slot reclaim, resume
# ---------------------------------------------------------------------

FLAT = {"x": hp.uniform("x", -5, 5)}
FLAT_AP = {"n_startup_jobs": 2, "n_EI_candidates": 8}
STALL = {"iteration_stop_count": 3}


def _drive_until_stopped(svc, study_id, limit=40):
    """Flat-loss reports until the SH5xx hook fires on suggest."""
    for i in range(limit):
        try:
            (t,) = svc.suggest(study_id, n=1)
        except StudyStopped:
            return i
        svc.report(study_id, t["tid"], loss=1.0)
    raise AssertionError("early stop never fired")


class TestActuation:
    def test_create_validates_early_stop_config(self):
        svc = OptimizationService(root=None, batch_window=0.001)
        try:
            with pytest.raises(ValueError):
                svc.create_study("a", FLAT, early_stop={"bogus": 1})
            with pytest.raises(ValueError):
                svc.create_study(
                    "a", FLAT,
                    early_stop={"iteration_stop_count": 0},
                )
            with pytest.raises(ValueError):
                build_stop_fn("not-a-dict")
        finally:
            svc.close()

    def test_stop_is_terminal_and_releases_the_slot(self):
        svc = OptimizationService(
            root=None, batch_window=0.001, max_studies=1,
        )
        try:
            svc.create_study(
                "s1", FLAT, seed=0, algo_params=FLAT_AP,
                early_stop=STALL,
            )
            n = _drive_until_stopped(svc, "s1")
            assert n >= FLAT_AP["n_startup_jobs"]
            status = svc.study_status("s1")
            assert status["status"] == "stopped"
            assert status["stopped"]["rule"] in STOP_RULES
            assert status["early_stop"] == STALL
            # terminal for NEW work: suggest keeps raising
            with pytest.raises(StudyStopped):
                svc.suggest("s1", n=1)
            # the admission slot is released: a queued study admits
            # under max_studies=1 even though s1 still exists
            svc.create_study("s2", FLAT, seed=1, algo_params=FLAT_AP)
            # ... which means resume needs capacity and must refuse
            with pytest.raises(BackpressureError):
                svc.resume_study("s1")
            counters = svc.control_stats.control_metrics()
            assert counters["reclaimed_studies_total"] == 1
            metrics = svc.metrics_text()
            assert "hyperopt_control_reclaimed_studies_total 1" in metrics
        finally:
            svc.close()

    def test_resume_reverses_the_stop(self):
        svc = OptimizationService(root=None, batch_window=0.001)
        try:
            svc.create_study(
                "s1", FLAT, seed=0, algo_params=FLAT_AP,
                early_stop=STALL,
            )
            _drive_until_stopped(svc, "s1")
            out = svc.resume_study("s1")
            assert out["status"] == "active"
            assert svc.study_status("s1")["stopped"] is None
            # suggests flow again after the resume
            (t,) = svc.suggest("s1", n=1)
            svc.report("s1", t["tid"], loss=0.5)
            counters = svc.control_stats.control_metrics()
            assert counters["resumed_studies_total"] == 1
        finally:
            svc.close()

    def test_studies_without_opt_in_never_stop(self):
        svc = OptimizationService(root=None, batch_window=0.001)
        try:
            svc.create_study("s", FLAT, seed=0, algo_params=FLAT_AP)
            for _ in range(12):  # flat losses, stall window exceeded
                (t,) = svc.suggest("s", n=1)
                svc.report("s", t["tid"], loss=1.0)
            status = svc.study_status("s")
            assert status["status"] == "active"
            assert status["early_stop"] is None
        finally:
            svc.close()

    def test_early_stop_config_survives_restart(self, tmp_path):
        root = str(tmp_path / "r")
        svc = OptimizationService(root=root, batch_window=0.001)
        try:
            svc.create_study(
                "s", FLAT, seed=0, algo_params=FLAT_AP,
                early_stop=STALL,
            )
        finally:
            svc.close()
        svc = OptimizationService(root=root, batch_window=0.001)
        try:
            status = svc.study_status("s")
            assert status["early_stop"] == STALL
            # exist_ok matches on the early_stop config too
            svc.create_study(
                "s", FLAT, seed=0, algo_params=FLAT_AP,
                early_stop=STALL, exist_ok=True,
            )
            with pytest.raises(StudyExists):
                svc.create_study(
                    "s", FLAT, seed=0, algo_params=FLAT_AP,
                    early_stop={"iteration_stop_count": 9},
                    exist_ok=True,
                )
            # the restarted hook still fires
            _drive_until_stopped(svc, "s")
            assert svc.study_status("s")["status"] == "stopped"
        finally:
            svc.close()


# ---------------------------------------------------------------------
# static-analysis coverage of the control package
# ---------------------------------------------------------------------


class TestLintCoverage:
    def test_race_lint_covers_control_package(self):
        """The control plane's locks (KnobSet table, controller
        decision ring) are auto-discovered by the race pass and lint
        clean — the zero-diagnostics green is not vacuous for this
        package."""
        from hyperopt_tpu.analysis import (
            discover_race_files,
            format_report,
            lint_races,
        )

        control_files = {
            os.path.basename(p)
            for p in discover_race_files()
            if os.sep + "control" + os.sep in p
        }
        assert {"knobs.py", "controller.py"} <= control_files
        diags = [
            d for d in lint_races()
            if os.sep + "control" + os.sep in d.location
        ]
        assert diags == [], format_report(diags)


# ---------------------------------------------------------------------
# HTTP plane: /v1/config, 403 non-loopback, 409 stopped, resume route
# ---------------------------------------------------------------------


class TestControlHTTP:
    def test_get_and_post_config(self):
        with ServiceServer(
            OptimizationService(root=None, batch_window=0.004)
        ) as server:
            client = ServiceClient(server.url)
            cfg = client.get_config()
            assert cfg["knobs"]["batch_window"]["value"] == 0.004
            assert cfg["control_enabled"] is False
            out = client.set_config(knobs={"batch_window": 0.002})
            assert out["values"]["batch_window"] == 0.002
            with pytest.raises(ServiceClientError) as e:
                client.set_config(knobs={"max_batch": 0})
            assert e.value.status == 400
            out = client.set_config(revert=True)
            assert out["is_static"] is True
            # the write's provenance names the API source
            sources = [
                p["source"] for p in client.get_config()["provenance"]
            ]
            assert any(s.startswith("api:") for s in sources)

    def test_post_config_refused_off_loopback(self, monkeypatch):
        from hyperopt_tpu.service import server as server_mod

        with ServiceServer(
            OptimizationService(root=None)
        ) as server:
            client = ServiceClient(server.url)
            monkeypatch.setattr(
                server_mod._Handler, "_is_loopback", lambda self: False
            )
            with pytest.raises(ServiceClientError) as e:
                client.set_config(knobs={"batch_window": 0.002})
            assert e.value.status == 403
            # reads stay open; only mutation is loopback-gated
            assert client.get_config()["knobs"]

    def test_stopped_maps_to_409_and_resume_route(self):
        with ServiceServer(
            OptimizationService(root=None, batch_window=0.001)
        ) as server:
            client = ServiceClient(server.url)
            client.create_study(
                "s", FLAT, seed=0, algo_params=FLAT_AP,
                early_stop=STALL,
            )
            status = None
            for _ in range(40):
                try:
                    (t,) = client.suggest("s")
                except ServiceClientError as e:
                    status = e.status
                    break
                client.report("s", t["tid"], loss=1.0)
            assert status == 409
            doc = client.study_status("s")
            assert doc["status"] == "stopped"
            out = client.resume_study("s")
            assert out["status"] == "active"
            (t,) = client.suggest("s")
            client.report("s", t["tid"], loss=0.5)
