"""TPE tests (reference parity: hyperopt/tests/test_tpe.py, the largest
suite): golden adaptive-Parzen cases, statistical sampler-vs-lpdf agreement,
seeded determinism, startup behavior, and optimization-quality thresholds
over the benchmark domain zoo.
"""

import numpy as np
import pytest

from hyperopt_tpu import Domain, Trials, fmin, hp
from hyperopt_tpu.algos import rand, tpe
from hyperopt_tpu.models import domains


class TestAdaptiveParzen:
    def test_empty_obs_prior_only(self):
        w, m, s = tpe.adaptive_parzen_normal([], 1.0, 0.0, 2.0)
        np.testing.assert_allclose(w, [1.0])
        np.testing.assert_allclose(m, [0.0])
        np.testing.assert_allclose(s, [2.0])

    def test_one_obs_below_prior(self):
        w, m, s = tpe.adaptive_parzen_normal([-1.0], 1.0, 0.0, 2.0)
        # sorted: obs at -1, prior at 0
        np.testing.assert_allclose(m, [-1.0, 0.0])
        np.testing.assert_allclose(s, [1.0, 2.0])  # obs sigma = prior/2
        np.testing.assert_allclose(w, [0.5, 0.5])

    def test_one_obs_above_prior(self):
        w, m, s = tpe.adaptive_parzen_normal([1.5], 2.0, 0.0, 2.0)
        np.testing.assert_allclose(m, [0.0, 1.5])
        np.testing.assert_allclose(s, [2.0, 1.0])
        np.testing.assert_allclose(w, [2 / 3, 1 / 3])

    def test_multi_obs_neighbor_gap_sigmas(self):
        # obs [1, 4, 6], prior at 0, prior_sigma 10
        w, m, s = tpe.adaptive_parzen_normal([4.0, 1.0, 6.0], 1.0, 0.0, 10.0)
        np.testing.assert_allclose(m, [0.0, 1.0, 4.0, 6.0])
        # sigma[1] = max(1-0, 4-1) = 3; sigma[2] = max(4-1, 6-4)=3;
        # sigma[3] (last) = 6-4 = 2; prior slot = prior_sigma
        assert s[0] == 10.0
        np.testing.assert_allclose(s[1:], [3.0, 3.0, 2.0])
        np.testing.assert_allclose(w, [0.25, 0.25, 0.25, 0.25])

    def test_sigma_clipping(self):
        # duplicate observations -> zero gaps clipped to minsigma
        w, m, s = tpe.adaptive_parzen_normal([5.0, 5.0, 5.0], 1.0, 0.0, 1.0)
        minsigma = 1.0 / min(100.0, 1.0 + 4.0)
        assert np.all(s[1:] >= minsigma - 1e-6)

    def test_prior_insertion_position(self):
        w, m, s = tpe.adaptive_parzen_normal([1.0, 3.0], 1.0, 2.0, 5.0)
        np.testing.assert_allclose(m, [1.0, 2.0, 3.0])
        assert s[1] == 5.0  # prior slot keeps prior sigma

    def test_linear_forgetting_downweights_old(self):
        obs = list(np.linspace(-3, 3, 40))
        w, m, s = tpe.adaptive_parzen_normal(obs, 1.0, 0.0, 6.0, LF=10)
        # chronologically-oldest obs is obs[0] = -3.0 (smallest -> index 0
        # or 1 in sorted order, after prior at pos of 0.0)
        idx_old = int(np.argmin(np.abs(m - (-3.0))))
        idx_new = int(np.argmin(np.abs(m - 3.0)))
        assert w[idx_old] < w[idx_new]

    def test_matches_reference_weight_function(self):
        np.testing.assert_allclose(
            tpe.linear_forgetting_weights(30, 25),
            np.concatenate([np.linspace(1 / 30, 1.0, 5), np.ones(25)]),
        )
        np.testing.assert_allclose(tpe.linear_forgetting_weights(10, 25), np.ones(10))


class TestGMMStatistical:
    """Histogram-vs-exp(lpdf) agreement — the reference's signature test."""

    def _hist_check(self, samples, lpdf_fn, lo, hi, atol=0.05):
        nbins = 30
        hist, edges = np.histogram(samples, bins=nbins, range=(lo, hi), density=True)
        centers = 0.5 * (edges[1:] + edges[:-1])
        dens = np.exp(lpdf_fn(centers))
        assert np.allclose(hist, dens, atol=atol * dens.max() + 0.02), (
            np.abs(hist - dens).max()
        )

    def test_gmm1_bounded(self):
        w = [0.4, 0.6]
        mu = [1.0, 4.0]
        s = [0.7, 1.2]
        rng = np.random.default_rng(0)
        x = tpe.GMM1(w, mu, s, low=0.0, high=6.0, rng=rng, size=(40000,))
        assert x.min() >= 0.0 and x.max() < 6.0
        self._hist_check(
            x, lambda c: tpe.GMM1_lpdf(c, w, mu, s, low=0.0, high=6.0), 0.0, 6.0
        )

    def test_gmm1_unbounded(self):
        w = [1.0]
        mu = [2.0]
        s = [1.5]
        x = tpe.GMM1(w, mu, s, rng=np.random.default_rng(1), size=(40000,))
        self._hist_check(x, lambda c: tpe.GMM1_lpdf(c, w, mu, s), -3.0, 7.0)

    def test_gmm1_lpdf_integrates_to_one(self):
        w = [0.3, 0.7]
        mu = [-1.0, 2.0]
        s = [0.5, 1.0]
        grid = np.linspace(-2.0, 4.0, 4001)
        dens = np.exp(tpe.GMM1_lpdf(grid, w, mu, s, low=-2.0, high=4.0))
        integral = np.trapezoid(dens, grid)
        assert abs(integral - 1.0) < 0.01

    def test_lgmm1_support_and_density(self):
        w = [1.0]
        mu = [0.5]
        s = [0.6]
        x = tpe.LGMM1(w, mu, s, rng=np.random.default_rng(2), size=(40000,))
        assert x.min() > 0
        grid = np.linspace(0.05, 8.0, 2001)
        dens = np.exp(tpe.LGMM1_lpdf(grid, w, mu, s))
        # analytic lognormal pdf
        ref = np.exp(-0.5 * ((np.log(grid) - 0.5) / 0.6) ** 2) / (
            grid * 0.6 * np.sqrt(2 * np.pi)
        )
        np.testing.assert_allclose(dens, ref, rtol=0.05, atol=1e-3)

    def test_lgmm1_bounded_support(self):
        w = [1.0]
        mu = [0.0]
        s = [1.0]
        lo, hi = np.log(0.5), np.log(4.0)
        x = tpe.LGMM1(w, mu, s, low=lo, high=hi, rng=np.random.default_rng(3), size=(20000,))
        assert x.min() >= 0.5 - 1e-6 and x.max() <= 4.0 + 1e-6

    def test_qgmm_discrete_probs_sum_to_one(self):
        w = [0.5, 0.5]
        mu = [2.0, 6.0]
        s = [1.0, 1.0]
        q = 1.0
        vals = np.arange(0.0, 9.0, q)
        ll = tpe.GMM1_lpdf(vals, w, mu, s, low=0.0, high=8.0, q=q)
        total = np.exp(ll).sum()
        assert abs(total - 1.0) < 0.02

    def test_qgmm_sampler_matches_pmf(self):
        w = [1.0]
        mu = [3.0]
        s = [2.0]
        q = 1.0
        rng = np.random.default_rng(4)
        x = tpe.GMM1(w, mu, s, low=0.0, high=8.0, q=q, rng=rng, size=(40000,))
        vals, counts = np.unique(x, return_counts=True)
        freq = counts / counts.sum()
        pmf = np.exp(tpe.GMM1_lpdf(vals, w, mu, s, low=0.0, high=8.0, q=q))
        np.testing.assert_allclose(freq, pmf, atol=0.015)

    def test_gmm1_seeded_deterministic(self):
        w, mu, s = [1.0], [0.0], [1.0]
        a = tpe.GMM1(w, mu, s, rng=np.random.default_rng(9), size=(10,))
        b = tpe.GMM1(w, mu, s, rng=np.random.default_rng(9), size=(10,))
        np.testing.assert_array_equal(a, b)


class TestSplit:
    def test_ap_split_counts(self):
        tids = np.arange(100)
        losses = np.arange(100, dtype=float)
        below = tpe.ap_split_trials(tids, losses, gamma=0.25)
        # ceil(0.25 * 10) = 3
        assert below == frozenset([0, 1, 2])

    def test_ap_split_capped_by_lf(self):
        tids = np.arange(10000)
        losses = np.random.default_rng(0).standard_normal(10000)
        below = tpe.ap_split_trials(tids, losses, gamma=0.9, gamma_cap=25)
        assert len(below) == 25


class TestSuggest:
    def test_startup_uses_random(self):
        d = domains.get("quadratic1")
        domain = Domain(d.fn, d.space)
        trials = Trials()
        ids = trials.new_trial_ids(1)
        docs_tpe = tpe.suggest(ids, domain, trials, seed=5)
        docs_rand = rand.suggest(ids, domain, Trials(), seed=5)
        assert (
            docs_tpe[0]["misc"]["vals"]["x"] == docs_rand[0]["misc"]["vals"]["x"]
        )

    def test_suggest_deterministic(self):
        d = domains.get("quadratic1")
        trials = Trials()
        fmin(
            d.fn, d.space, algo=rand.suggest, max_evals=25, trials=trials,
            rstate=np.random.default_rng(0), show_progressbar=False, verbose=False,
        )
        domain = Domain(d.fn, d.space)
        ids = [100]
        a = tpe.suggest(ids, domain, trials, seed=3)
        b = tpe.suggest(ids, domain, trials, seed=3)
        assert a[0]["misc"]["vals"] == b[0]["misc"]["vals"]

    def test_suggest_batch_of_ids(self):
        d = domains.get("branin")
        trials = Trials()
        fmin(
            d.fn, d.space, algo=rand.suggest, max_evals=25, trials=trials,
            rstate=np.random.default_rng(0), show_progressbar=False, verbose=False,
        )
        domain = Domain(d.fn, d.space)
        docs = tpe.suggest([100, 101, 102], domain, trials, seed=0)
        assert len(docs) == 3
        xs = [doc["misc"]["vals"]["x"][0] for doc in docs]
        assert len(set(xs)) == 3  # independent candidate draws per id

    def test_conditional_space_active_labels_only(self):
        d = domains.get("q1_choice")
        trials = Trials()
        fmin(
            d.fn, d.space, algo=rand.suggest, max_evals=30, trials=trials,
            rstate=np.random.default_rng(0), show_progressbar=False, verbose=False,
        )
        domain = Domain(d.fn, d.space)
        docs = tpe.suggest(list(range(100, 120)), domain, trials, seed=1)
        for doc in docs:
            m = doc["misc"]
            assert (len(m["idxs"]["xl"]) == 1) != (len(m["idxs"]["xr"]) == 1)
            choice = m["vals"]["mode"][0]
            if choice == 0:
                assert len(m["idxs"]["xl"]) == 1
            else:
                assert len(m["idxs"]["xr"]) == 1

    def test_startup_gate_counts_inserted_trials(self):
        # reference gates on len(trials.trials) (all inserted, non-error),
        # not completed-OK count: with RUNNING/FAIL trials padding the
        # store past n_startup_jobs, TPE must leave random search
        from hyperopt_tpu.base import (
            JOB_STATE_DONE,
            JOB_STATE_RUNNING,
            STATUS_OK,
            STATUS_RUNNING,
        )

        d = domains.get("quadratic1")
        domain = Domain(d.fn, d.space)
        trials = Trials()
        docs = []
        rng = np.random.default_rng(0)
        for i in range(25):
            misc = {
                "tid": i, "cmd": None,
                "idxs": {"x": [i]}, "vals": {"x": [float(rng.uniform(-5, 5))]},
            }
            done = i < 5  # only 5 completed-OK; 20 still running
            docs.append({
                "tid": i, "spec": None,
                "result": (
                    {"status": STATUS_OK, "loss": float(rng.normal())}
                    if done else {"status": STATUS_RUNNING}
                ),
                "misc": misc,
                "state": JOB_STATE_DONE if done else JOB_STATE_RUNNING,
                "owner": None, "book_time": None, "refresh_time": None,
                "exp_key": None,
            })
        trials._insert_trial_docs(docs)
        trials.refresh()
        assert len(trials.trials) == 25  # gate input
        assert len(trials.history.losses) == 5
        a = tpe.suggest([100], domain, trials, seed=3, n_startup_jobs=20)
        b = rand.suggest([100], domain, Trials(), seed=3)
        # past the gate: TPE path, so the draw differs from plain random
        assert a[0]["misc"]["vals"]["x"] != b[0]["misc"]["vals"]["x"]

    def test_partial_config_pattern(self):
        from functools import partial

        d = domains.get("quadratic1")
        algo = partial(tpe.suggest, n_startup_jobs=5, n_EI_candidates=50, gamma=0.3)
        trials = Trials()
        fmin(
            d.fn, d.space, algo=algo, max_evals=30, trials=trials,
            rstate=np.random.default_rng(0), show_progressbar=False, verbose=False,
        )
        assert len(trials) == 30


@pytest.mark.parametrize(
    "name", ["quadratic1", "q1_choice", "gauss_wave", "branin", "distractor"]
)
def test_tpe_quality_on_domains(name):
    """Optimization-quality thresholds per domain (the reference's
    conformance style: best loss below bound after fixed trials)."""
    d = domains.get(name)
    results = []
    for seed in (123, 0):  # best-of-2 seeds: thresholds are conformance
        trials = Trials()   # bounds, not luck (multi-modal domains vary)
        fmin(
            d.fn,
            d.space,
            algo=tpe.suggest,
            max_evals=d.quality_evals,
            trials=trials,
            rstate=np.random.default_rng(seed),
            show_progressbar=False,
            verbose=False,
        )
        results.append(min(trials.losses()))
        if min(results) < d.quality_threshold:
            break
    assert min(results) < d.quality_threshold, (name, results, d.quality_threshold)


def test_tpe_beats_random_on_distractor():
    """Guided search must find the narrow global basin more reliably."""
    d = domains.get("distractor")

    def best_of(algo, seed):
        trials = Trials()
        fmin(
            d.fn, d.space, algo=algo, max_evals=100, trials=trials,
            rstate=np.random.default_rng(seed), show_progressbar=False, verbose=False,
        )
        return min(trials.losses())

    tpe_scores = [best_of(tpe.suggest, s) for s in range(3)]
    rand_scores = [best_of(rand.suggest, s) for s in range(3)]
    assert np.mean(tpe_scores) <= np.mean(rand_scores) + 0.05


# ---------------------------------------------------------------------
# Observation filtering: param_locks + trial_filter (the ATPE cascade
# plumbing — reference resultFilteringMode / per-param filtering)
# ---------------------------------------------------------------------


def _two_cluster_trials(n_per=20):
    """History with a good cluster at x≈-5 and a bad cluster at x≈+5."""
    from hyperopt_tpu.base import JOB_STATE_DONE, STATUS_OK

    rng = np.random.default_rng(0)
    trials = Trials()
    docs = []
    for i in range(2 * n_per):
        good = i % 2 == 0
        x = rng.normal(-5.0 if good else 5.0, 0.3)
        loss = (0.1 if good else 10.0) + rng.normal(0, 0.01)
        misc = {"tid": i, "cmd": None, "idxs": {"x": [i]}, "vals": {"x": [float(x)]}}
        docs.append({
            "tid": i, "spec": None,
            "result": {"status": STATUS_OK, "loss": float(loss)},
            "misc": misc, "state": JOB_STATE_DONE,
            "owner": None, "book_time": None, "refresh_time": None,
            "exp_key": None,
        })
    trials._insert_trial_docs(docs)
    trials.refresh()
    return trials


class TestObsFiltering:
    def setup_method(self):
        self.space = {"x": hp.uniform("x", -10, 10)}
        self.domain = Domain(lambda c: 0.0, self.space)
        self.trials = _two_cluster_trials()

    def _suggest_xs(self, **kw):
        docs = tpe.suggest(
            list(range(1000, 1016)), self.domain, self.trials, seed=11, **kw
        )
        return np.array([d["misc"]["vals"]["x"][0] for d in docs])

    def test_unlocked_follows_good_cluster(self):
        xs = self._suggest_xs()
        assert np.median(xs) < 0  # posterior tracks the low-loss cluster

    def test_hard_lock_pins_value(self):
        # radius <= 0: the reference's lockedValues — value pinned exactly
        xs = self._suggest_xs(param_locks={"x": (3.21, 0.0)})
        np.testing.assert_allclose(xs, 3.21)

    def test_soft_lock_concentrates_near_incumbent(self):
        # radius > 0 with center at the incumbent best: observations are
        # filtered to the neighborhood, so suggestions concentrate there
        xs = self._suggest_xs(param_locks={"x": (-5.0, 1.0)})
        assert np.all(np.abs(xs + 5.0) < 3.0), xs

    def test_soft_lock_outside_support_is_ignored(self):
        # a neighborhood disjoint from the label's support would invert
        # the truncation bounds; the lock is ignored instead
        xs = self._suggest_xs(param_locks={"x": (40.0, 0.5)})
        assert np.all(np.isfinite(xs))
        assert np.all(xs >= -10) and np.all(xs <= 10)
        assert len(np.unique(np.round(xs, 6))) > 1  # not a degenerate point

    def test_trial_filter_mask_restricts_posterior(self):
        hist = self.trials.history
        # keep only the bad cluster's trials: posterior must follow it
        mask = np.array([t % 2 == 1 for t in hist.loss_tids])
        xs = self._suggest_xs(trial_filter=mask)
        assert np.median(xs) > 0, xs

    def test_trial_filter_callable(self):
        xs = self._suggest_xs(
            trial_filter=lambda h: np.asarray(h.loss_tids) % 2 == 1
        )
        assert np.median(xs) > 0, xs

    def test_trial_filter_bad_shape_raises(self):
        with pytest.raises(ValueError):
            self._suggest_xs(trial_filter=np.ones(3, dtype=bool))


class TestBucketGridScoring:
    """Bounded quantized dists score on the distinct-value grid and
    gather per candidate; must match the per-candidate exact path."""

    def test_grid_matches_per_candidate(self):
        import jax

        from hyperopt_tpu.algos import tpe_device as td

        L, CAP, CAPT = 2, 64, 64
        rng = np.random.default_rng(0)
        keys = np.asarray(
            jax.random.split(jax.random.PRNGKey(0), L), np.uint32
        )
        # quniform(0, 100, 5) style labels
        obs = np.round(rng.uniform(0, 100, (L, CAP)) / 5) * 5
        obs = obs.astype(np.float32)
        pos = np.tile(np.arange(CAP, dtype=np.int32), (L, 1))
        counts = np.full(L, 40, np.int32)
        losses = rng.normal(size=CAPT).astype(np.float32)
        keep = np.ones(CAPT, bool)
        priors = np.tile(
            np.array([50.0, 100.0, 0.0, 100.0, 5.0], np.float32), (L, 1)
        )
        lock_c = np.zeros(L, np.float32)
        lock_r = np.full(L, np.inf, np.float32)
        args = (
            keys, obs, pos, counts, losses, keep,
            np.int32(4), np.float32(1.0), priors, lock_c, lock_r,
        )
        common = dict(cap_b=8, k=2, n_cand=128, lf=25,
                      log_scale=False, quantized=True, scorer="xla")
        from functools import partial

        # the core returns (winners, diag) since the search-health
        # telemetry rides the fused program; the winner contract is [0]
        per_cand = np.asarray(
            jax.jit(partial(td._family_suggest_core, n_buckets=0, **common))(*args)[0]
        )
        grid = np.asarray(
            jax.jit(partial(td._family_suggest_core, n_buckets=24, **common))(*args)[0]
        )
        np.testing.assert_allclose(grid, per_cand)

    def test_bucket_count_gating(self):
        from hyperopt_tpu.algos.tpe import _MAX_GRID_BUCKETS, _family_bucket_count

        def fam(pri, log_scale=False):
            class FakeFam:
                pass

            f = FakeFam()
            f.L = len(pri)
            f.log_scale = log_scale
            f.default_priors = np.asarray(pri, np.float32)
            return f

        pri = [[50, 100, 0, 100, 5], [10, 20, 0, 20, 1]]
        assert _family_bucket_count(fam(pri), 8192) == 23  # ceil(100/5)+3
        # unbounded label -> 0 (per-candidate path)
        assert _family_bucket_count(fam([[0, 1, -np.inf, np.inf, 1]]), 8192) == 0
        # oversized grid -> 0
        assert _family_bucket_count(
            fam([[0, 1, 0, 10 * _MAX_GRID_BUCKETS, 1]]), 10**6
        ) == 0
        # grid not smaller than the candidate count -> 0 (no saving)
        assert _family_bucket_count(fam(pri), 16) == 0

    def test_mixed_bounded_unbounded_quantized_split(self):
        """A qnormal label must not disable the bucket grid for a
        quniform label — they land in different device families."""
        from hyperopt_tpu import Domain, hp
        from hyperopt_tpu.algos import tpe_device as td

        space = {
            "w": hp.quniform("w", 0, 100, 5),
            "g": hp.qnormal("g", 0, 10, 1),
        }
        domain = Domain(lambda c: 0.0, space)
        dh = td.DeviceHistory(domain.space.specs)
        quant_keys = [k for k in dh.families if k[0] == "cont" and k[2]]
        assert len(quant_keys) == 2  # split by boundedness
        bounded = [k for k in quant_keys if k[3]]
        assert len(bounded) == 1
