"""Pull-based sealed-segment replication (PR 16 tentpole, service leg).

A failover target pre-warms a study WITHOUT a shared filesystem root by
pulling the owner's sealed trial-log segments (fence-checked cut
points, CRC-verified byte copies, manifest published last).  The gate
here is the twin campaign: a study served on replica A, cut, mirrored
into replica B's own root, and continued on B must produce a
trial-for-trial identical trajectory to a single-replica twin — and
the rebuilt root must be fsck-clean.
"""

import os
import time

import pytest

from hyperopt_tpu import hp
from hyperopt_tpu.parallel.file_trials import FileTrials
from hyperopt_tpu.resilience.fsck import fsck_queue
from hyperopt_tpu.service import OptimizationService
from hyperopt_tpu.service.replicas import SegmentMirror, StudyLeaseStore

SPACE = {"x": hp.uniform("x", -5, 5)}
AP = {"n_startup_jobs": 2, "n_EI_candidates": 8}


def _seed_study(root, study_id="s", n_trials=4):
    """A segmented FileTrials study dir with ``n_trials`` inserted docs
    and a sealed active segment (the graceful cut)."""
    qdir = os.path.join(root, "studies", study_id)
    ft = FileTrials(qdir)
    tids = ft.new_trial_ids(n_trials)
    ft._insert_trial_docs(
        [{"tid": t, "state": 0, "misc": {"tid": t}} for t in tids]
    )
    ft.jobs.segments.seal_active()
    return qdir, tids


class TestSegmentMirror:
    def test_pull_is_verified_idempotent_and_replayable(self, tmp_path):
        src, dst = str(tmp_path / "a"), str(tmp_path / "b")
        _seed_study(src, n_trials=5)
        StudyLeaseStore(src).claim("s", "owner-a")

        mirror = SegmentMirror(src, dst)
        out = mirror.pull_study("s")
        assert out["ok"] and out["n_pulled"] == 1
        # idempotent: a second pull re-copies nothing (sealed segments
        # are immutable; presence-at-size is the skip test)
        again = mirror.pull_study("s")
        assert again["ok"] and again["n_pulled"] == 0
        # the pulled root replays to the same trial set
        ft = FileTrials(os.path.join(dst, "studies", "s"))
        ft.refresh()
        assert sorted(d["tid"] for d in ft._dynamic_trials) == [
            0, 1, 2, 3, 4,
        ]

    def test_corrupt_source_segment_is_refused(self, tmp_path):
        src, dst = str(tmp_path / "a"), str(tmp_path / "b")
        qdir, _ = _seed_study(src)
        seg_dir = os.path.join(qdir, "segments")
        name = next(
            n for n in sorted(os.listdir(seg_dir)) if n.startswith("seg-")
        )
        with open(os.path.join(seg_dir, name), "r+b") as f:
            f.seek(10)
            f.write(b"XXXX")
        out = SegmentMirror(src, dst).pull_study("s")
        assert not out["ok"]
        assert "CRC" in out["reason"]
        # nothing was published: no manifest, so the dst replays empty
        assert not os.path.exists(
            os.path.join(dst, "studies", "s", "segments", "MANIFEST.json")
        )

    def test_fence_move_mid_pull_withholds_manifest(self, tmp_path):
        src, dst = str(tmp_path / "a"), str(tmp_path / "b")
        _seed_study(src)
        real = StudyLeaseStore(src)
        real.claim("s", "owner-a")

        class MovingFence(StudyLeaseStore):
            def __init__(self):
                super().__init__(src)
                self.calls = 0

            def read_fence(self, study_id):
                self.calls += 1
                base = super().read_fence(study_id)
                return base if self.calls == 1 else base + 1

        mirror = SegmentMirror(src, dst)
        mirror.leases = MovingFence()
        out = mirror.pull_study("s")
        assert not out["ok"] and "fence moved" in out["reason"]
        # the copied segments are kept (immutable, reusable) but the
        # manifest is withheld — the dst store sees no study yet
        dst_segs = os.path.join(dst, "studies", "s", "segments")
        assert any(
            n.startswith("seg-") for n in os.listdir(dst_segs)
        )
        assert not os.path.exists(
            os.path.join(dst_segs, "MANIFEST.json")
        )

    def test_same_root_is_refused(self, tmp_path):
        with pytest.raises(ValueError):
            SegmentMirror(str(tmp_path), str(tmp_path))

    def test_pull_all_covers_every_study(self, tmp_path):
        src, dst = str(tmp_path / "a"), str(tmp_path / "b")
        _seed_study(src, study_id="s1", n_trials=2)
        _seed_study(src, study_id="s2", n_trials=3)
        outs = SegmentMirror(src, dst).pull_all()
        assert [o["study"] for o in outs] == ["s1", "s2"]
        assert all(o["ok"] for o in outs)

    def test_pull_refused_once_destination_owns_the_study(self, tmp_path):
        """After a takeover the claim lives in the DESTINATION's lease
        plane — the dead owner's source fence never moves again, so a
        fence check against the source cannot protect the live local
        state.  pull_study must refuse outright, and the reaper-tick
        pull_all honors the replica set's ownership skip."""
        src, dst = str(tmp_path / "a"), str(tmp_path / "b")
        _seed_study(src, n_trials=3)
        mirror = SegmentMirror(src, dst)
        assert mirror.pull_study("s")["ok"]  # pre-takeover pull works

        # the takeover: the destination claims the study in its OWN
        # root and keeps serving — its log grows past the snapshot
        StudyLeaseStore(dst).claim("s", "rb")
        ft = FileTrials(os.path.join(dst, "studies", "s"))
        ft.refresh()
        (tid,) = ft.new_trial_ids(1)
        ft._insert_trial_docs(
            [{"tid": tid, "state": 0, "misc": {"tid": tid}}]
        )

        out = mirror.pull_study("s")
        assert not out["ok"] and "live-owned" in out["reason"]
        # the post-takeover record survived: no stale overwrite of the
        # manifest / sidecars, and no re-issued trial id
        ft2 = FileTrials(os.path.join(dst, "studies", "s"))
        ft2.refresh()
        assert tid in [d["tid"] for d in ft2._dynamic_trials]
        assert ft2.new_trial_ids(1)[0] == tid + 1
        # the ownership predicate short-circuits pull_all entirely
        assert mirror.pull_all(skip=lambda sid: sid == "s") == []

    def test_repeat_pull_does_not_churn_identical_state(self, tmp_path):
        """A no-change re-pull must not republish the manifest or
        sidecars: every atomic replace on the destination races a
        concurrently-starting reader there, so byte-identical copies
        stay untouched."""
        src, dst = str(tmp_path / "a"), str(tmp_path / "b")
        _seed_study(src, n_trials=3)
        mirror = SegmentMirror(src, dst)
        assert mirror.pull_study("s")["ok"]
        manifest = os.path.join(
            dst, "studies", "s", "segments", "MANIFEST.json"
        )
        counter = os.path.join(dst, "studies", "s", "ids.counter")
        sig = (
            os.stat(manifest).st_mtime_ns,
            os.stat(manifest).st_ino,
            os.stat(counter).st_mtime_ns,
            os.stat(counter).st_ino,
        )
        again = mirror.pull_study("s")
        assert again["ok"] and again["n_pulled"] == 0
        assert again["nbytes"] == 0
        assert sig == (
            os.stat(manifest).st_mtime_ns,
            os.stat(manifest).st_ino,
            os.stat(counter).st_mtime_ns,
            os.stat(counter).st_ino,
        )


class TestTwinTrajectoryFailover:
    @pytest.mark.slow
    def test_failover_target_rebuilds_from_pulled_segments(self, tmp_path):
        """Two-replica campaign vs single-replica twin.

        Replica A serves 3 trials on its own root; the cut seals A's
        active segment; the mirror pulls the sealed log + sidecars into
        replica B's OWN root (no shared filesystem); B adopts the study
        from its local copy and serves 3 more.  The combined 6-trial
        trajectory must be trial-for-trial identical to one service
        running all 6 — and B's rebuilt study dir must be fsck-clean.
        """
        objective = lambda x: (x - 1.0) ** 2  # noqa: E731

        def run_trials(svc, study_id, n):
            out = []
            for _ in range(n):
                (t,) = svc.suggest(study_id, n=1)
                x = t["vals"]["x"]
                svc.report(study_id, t["tid"], loss=objective(x))
                out.append((t["tid"], x))
            return out

        # the twin: one service, all six trials
        twin = OptimizationService(
            root=str(tmp_path / "twin"), batch_window=0.001, warmup=False
        )
        try:
            twin.create_study("mig", SPACE, seed=7, algo="tpe",
                              algo_params=AP)
            want = run_trials(twin, "mig", 6)
        finally:
            twin.close()

        root_a = str(tmp_path / "ra")
        root_b = str(tmp_path / "rb")
        s1 = OptimizationService(
            root=root_a, replica_id="ra", advertise_url="http://a",
            replica_ttl=30.0, batch_window=0.001, warmup=False,
        )
        try:
            s1.create_study("mig", SPACE, seed=7, algo="tpe",
                            algo_params=AP)
            first = run_trials(s1, "mig", 3)
            # graceful cut: seal the active segment so every record A
            # wrote is inside the pulled prefix
            study = s1.registry.get("mig")
            study.trials.jobs.segments.seal_active()
            out = SegmentMirror(root_a, root_b).pull_study("mig")
            assert out["ok"] and out["n_pulled"] >= 1
        finally:
            s1.close()

        # replica B starts on ITS OWN root — everything it knows about
        # the study arrived through the pull
        s2 = OptimizationService(
            root=root_b, replica_id="rb", advertise_url="http://b",
            replica_ttl=30.0, batch_window=0.001, warmup=False,
        )
        try:
            assert "mig" in s2.registry.list()
            st = s2.study_status("mig")
            assert st["n_completed"] == 3
            rest = run_trials(s2, "mig", 3)
        finally:
            s2.close()

        got = first + rest
        assert [tid for tid, _ in got] == [tid for tid, _ in want]
        for (_, gx), (_, wx) in zip(got, want):
            assert gx == pytest.approx(wx, abs=0.0)

        # the rebuilt root is structurally sound: full fsck, no findings
        report = fsck_queue(
            os.path.join(root_b, "studies", "mig"), repair=False
        )
        assert report.clean, report.findings

    def test_reaper_tick_pulls_through_attached_mirror(self, tmp_path):
        """ReplicaSet wiring: a mirror attached to the replica set is
        pulled on the reaper cadence, so the local copy tracks the
        owner's sealed cuts without any explicit pull call."""
        from hyperopt_tpu.service.replicas import ReplicaSet

        src, dst = str(tmp_path / "a"), str(tmp_path / "b")
        _seed_study(src, n_trials=3)
        rs = ReplicaSet(dst, "rb", url="http://b", ttl=0.2)
        rs.attach_mirror(SegmentMirror(src, dst, ttl=0.2))
        rs.bind(adopt=lambda sid, reason: False, relinquish=lambda sid: None)
        rs.start()
        try:
            deadline = time.monotonic() + 5.0
            manifest = os.path.join(
                dst, "studies", "s", "segments", "MANIFEST.json"
            )
            while time.monotonic() < deadline:
                if os.path.exists(manifest):
                    break
                time.sleep(0.05)
            assert os.path.exists(manifest)
        finally:
            rs.close()


class TestSharedCompileCacheRefusal:
    def test_live_sibling_sharing_cache_dir_is_refused(self, tmp_path):
        from hyperopt_tpu.service.replicas import ReplicaDirectory

        root = str(tmp_path / "root")
        cache = str(tmp_path / "cache")
        directory = ReplicaDirectory(root)
        directory.advertise("other", "http://other",
                            compile_cache_dir=cache)
        with pytest.raises(ValueError, match="compile cache dir"):
            OptimizationService(
                root=root, replica_id="me", advertise_url="http://me",
                compile_cache_dir=cache, warmup=False,
            )

    def test_unsafe_flag_allows_the_shared_dir(self, tmp_path):
        from hyperopt_tpu.service.replicas import ReplicaDirectory

        root = str(tmp_path / "root")
        cache = str(tmp_path / "cache")
        ReplicaDirectory(root).advertise(
            "other", "http://other", compile_cache_dir=cache
        )
        svc = OptimizationService(
            root=root, replica_id="me", advertise_url="http://me",
            compile_cache_dir=cache, warmup=False,
            unsafe_shared_compile_cache=True,
        )
        svc.close()

    def test_stale_sibling_record_does_not_refuse(self, tmp_path):
        """Only a LIVE record blocks: a dead replica's leftover record
        (or our own, from a restart) must not wedge startup."""
        import json as _json

        from hyperopt_tpu.service.replicas import ReplicaDirectory
        from hyperopt_tpu.parallel.file_trials import _write_doc

        root = str(tmp_path / "root")
        cache = str(tmp_path / "cache")
        directory = ReplicaDirectory(root)
        os.makedirs(directory.registry_dir, exist_ok=True)
        _write_doc(
            directory.record_path("dead"),
            {"replica_id": "dead", "url": "http://dead",
             "heartbeat_at": time.time() - 3600.0, "pid": 0,
             "compile_cache_dir": os.path.abspath(cache)},
            fsync_kind="attachment",
        )
        svc = OptimizationService(
            root=root, replica_id="me", advertise_url="http://me",
            compile_cache_dir=cache, warmup=False,
        )
        try:
            record = directory.lookup("me")
            assert record["compile_cache_dir"] == os.path.abspath(cache)
        finally:
            svc.close()
