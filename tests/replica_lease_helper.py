"""Subprocess body for the cross-process replica-lease contention
tests (tests/test_resilience.py::TestReplicaLeases).

Modes:

- ``race``: park until the parent drops a ``go`` file, then attempt ONE
  claim — two of these started together are a real two-process race on
  the ``O_CREAT|O_EXCL`` claim lock.
- ``cycle N``: N claim→release cycles, spinning while the peer holds
  the lease; prints the fence sequence this process observed.

Prints one JSON line on stdout; exit 0 on success.
"""

import json
import os
import sys
import time

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)


def main():
    root, study, rid, mode = sys.argv[1:5]
    from hyperopt_tpu.service.replicas import StudyLeaseStore

    store = StudyLeaseStore(root, ttl=5.0)
    if mode == "race":
        go = os.path.join(root, "go")
        deadline = time.time() + 30.0
        while not os.path.exists(go):
            if time.time() > deadline:
                print(json.dumps({"replica": rid, "error": "timeout"}))
                return 1
            time.sleep(0.001)
        fence = store.claim(study, rid)
        print(json.dumps({"replica": rid, "fence": fence}))
        return 0
    if mode == "cycle":
        n = int(sys.argv[5])
        fences = []
        deadline = time.time() + 60.0
        for _ in range(n):
            fence = None
            while fence is None:
                fence = store.claim(study, rid)
                if fence is None:
                    if time.time() > deadline:
                        print(json.dumps(
                            {"replica": rid, "error": "starved"}
                        ))
                        return 1
                    time.sleep(0.002)
            fences.append(fence)
            store.release(study, rid, fence)
        print(json.dumps({"replica": rid, "fences": fences}))
        return 0
    print(json.dumps({"replica": rid, "error": f"bad mode {mode}"}))
    return 2


if __name__ == "__main__":
    sys.exit(main())
