"""Tests for the SG7xx protocol pass + the explicit-state model checker.

Acceptance contract of the protocol tier:

- every SG7xx rule has at least one positive fixture (fires, and fires
  ONLY its intended id) and one negative fixture (the shipped idiom
  passes clean);
- the shipped tree protocol-lints to ZERO diagnostics and the
  small-scope model check finds no violation (the hard-gate baseline);
- **mutation validation**: each of the four PR 16 protocol bugs
  (post-takeover mirror clobber, non-contiguous cursor advance,
  orphan-sweep record loss, seal-lock break race) re-injected into its
  model scenario produces a violating trace, printed as a
  human-readable schedule — if a guard or invariant is ever weakened,
  these fail before the model silently passes everything;
- registry/docs drift: every rule id named in the docs is registered
  and every registered rule is documented (FS4xx fsck repair ids are
  checked against the fsck source the same way);
- the CI surfaces: scripts/lint.py ``--json`` schema + timing line +
  the 60-second ``--fast`` budget, and the ``__main__`` target
  inference (bare ``.py`` → race+durability, module → space pass).
"""

import json
import os
import re
import subprocess
import sys
import textwrap

import pytest

from hyperopt_tpu.analysis import (
    RULES,
    discover_protocol_files,
    lint_protocol,
    run_self_lint,
)
from hyperopt_tpu.analysis.protocol_lint import (
    ROLES,
    lint_source as pl_lint_source,
)
from hyperopt_tpu.analysis.protocol_model import (
    MUTATIONS,
    SCENARIOS,
    build_scenario,
    check_all,
    check_mutation,
    find_violation,
    format_schedule,
    model_check_diagnostics,
)

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_ENV = dict(os.environ, JAX_PLATFORMS="cpu")


def _rules(source):
    return [d.rule for d in pl_lint_source(textwrap.dedent(source))]


# ---------------------------------------------------------------------
# SG7xx fixture corpus: one positive + one negative per rule
# ---------------------------------------------------------------------


def test_sg705_sg701_missing_owner_and_fence():
    # a replication write with neither an ownership check nor a fence
    # validation: both disciplines fire, nothing else
    assert _rules("""
        def pull(dst):  # protocol: replication-write
            _atomic_write(dst + "/seg1", b"x")
            _write_doc(dst + "/manifest.json", {})
    """) == ["SG705", "SG701"]


def test_sg701_durable_write_between_fence_and_commit():
    assert _rules("""
        def pull(dst):  # protocol: replication-write
            owner_of(dst)
            read_fence(dst)
            _atomic_write(dst + "/sidecar", b"x")
            _write_doc(dst + "/manifest.json", {})
    """) == ["SG701"]


def test_sg702_write_after_manifest_publish():
    assert _rules("""
        def pull(dst):  # protocol: replication-write
            owner_of(dst)
            read_fence(dst)
            _write_doc(dst + "/manifest.json", {})
            _atomic_write(dst + "/sidecar", b"x")
    """) == ["SG702"]


def test_replication_write_shipped_idiom_clean():
    # ownership check first, payload copies, fence re-check, manifest
    # LAST — the pull_study shape
    assert _rules("""
        def pull(dst):  # protocol: replication-write
            if owner_of(dst):
                return
            _atomic_write(dst + "/seg1", b"x")
            _atomic_write(dst + "/sidecar", b"x")
            read_fence(dst)
            _write_doc(dst + "/manifest.json", {})
    """) == []


def test_sg703_max_cursor_advance_fires_file_wide():
    # no annotation needed: max()-advance of a cursorish target is
    # flagged anywhere in a protocol module
    assert _rules("""
        def advance(self, seg, end):
            self._offsets[seg] = max(self._offsets.get(seg, 0), end)
    """) == ["SG703"]


def test_sg703_unguarded_advance_in_declared_site():
    assert _rules("""
        def advance(self, seg, end, nbytes):  # protocol: cursor-advance
            self._offsets[seg] = end
    """) == ["SG703"]


def test_sg703_contiguity_guarded_advance_clean():
    # the PR 16 fixed idiom: advance only when contiguous with the
    # cursor; a gap is left for the next refresh to replay
    assert _rules("""
        def advance(self, seg, end, nbytes):  # protocol: cursor-advance
            if self._offsets.get(seg, 0) == end - nbytes:
                self._offsets[seg] = end
    """) == []


def test_sg704_shared_lock_unlink_in_acquire_path():
    assert _rules("""
        def acquire(lock_path):
            while True:
                try:
                    return os.open(lock_path, os.O_CREAT | os.O_EXCL)
                except FileExistsError:
                    os.unlink(lock_path)  # protocol: lock-break
                    continue
    """) == ["SG704"]


def test_sg704_rename_before_unlink_clean():
    # the fixed idiom: rename the lock to a private name first — only
    # one breaker wins the rename, so a fresh lock another breaker
    # re-created can never be removed
    assert _rules("""
        def acquire(lock_path):  # protocol: lock-break
            while True:
                try:
                    return os.open(lock_path, os.O_CREAT | os.O_EXCL)
                except FileExistsError:
                    stale = lock_path + ".stale"
                    os.rename(lock_path, stale)
                    os.unlink(stale)
                    continue
    """) == []


def test_sg701_orphan_sweep_without_rehome():
    assert _rules("""
        def sweep(orphans):  # protocol: orphan-sweep
            for path in orphans:
                os.unlink(path)
    """) == ["SG701"]


def test_orphan_sweep_with_rehome_clean():
    assert _rules("""
        def sweep(orphans, active):  # protocol: orphan-sweep
            for path, records in orphans:
                append_records(active, records)
                os.unlink(path)
    """) == []


def test_sg707_unknown_role():
    assert _rules("""
        def f():  # protocol: segment-write
            pass
    """) == ["SG707"]


def test_sg707_unattached_annotation():
    assert _rules("""
        # protocol: lock-break
        X = 1
    """) == ["SG707"]


def test_annotation_attaches_line_above_and_enclosing():
    # line-above and innermost-enclosing attachment both govern the
    # same def as the same-line form
    above = _rules("""
        # protocol: orphan-sweep
        def sweep(orphans):
            for path in orphans:
                os.unlink(path)
    """)
    inside = _rules("""
        def sweep(orphans):
            # protocol: orphan-sweep
            for path in orphans:
                os.unlink(path)
    """)
    assert above == inside == ["SG701"]


def test_annotation_inside_string_is_not_parsed():
    # mirroring the race pass: grammar examples in docstrings are inert
    assert _rules('''
        def helper():
            """Document the marker: # protocol: orphan-sweep ."""
            os.unlink("scratch")
    ''') == []


def test_sg_lint_disable_comment_suppression():
    src = textwrap.dedent("""
        def advance(self, seg, end):
            self._offsets[seg] = max(self._offsets.get(seg, 0), end)  # lint: disable=SG703
    """)
    assert pl_lint_source(src) == []


# ---------------------------------------------------------------------
# shipped-tree baseline: discovery + zero diagnostics
# ---------------------------------------------------------------------


def test_discovery_finds_exactly_the_protocol_modules():
    names = {os.path.basename(p) for p in discover_protocol_files()}
    assert names == {"segment_store.py", "replicas.py", "fsck.py"}


def test_repo_protocol_lint_zero_diagnostics():
    assert lint_protocol() == []


def test_self_lint_sections_include_protocol_and_model():
    sections = run_self_lint(static_only=True)
    keys = [k for k, _h, _d, _s in sections]
    assert keys == ["race", "durability", "program", "protocol", "model"]
    for _k, _h, diags, secs in sections:
        assert diags == []
        assert secs >= 0.0


# ---------------------------------------------------------------------
# Tier B: model checker — clean protocols pass, every PR 16 bug caught
# ---------------------------------------------------------------------


def test_model_clean_scenarios_no_violation():
    results = check_all()
    assert {n for n, _ in results} == set(SCENARIOS)
    for name, violation in results:
        assert violation is None, format_schedule(violation)


def test_model_check_diagnostics_empty_on_shipped_protocol():
    assert model_check_diagnostics() == []


@pytest.mark.parametrize("bug", sorted(MUTATIONS))
def test_model_mutation_validation(bug):
    """Re-inject each PR 16 bug into its scenario: the checker must
    find a violating trace and print it as a readable schedule."""
    violation = check_mutation(bug)
    assert violation is not None, f"model failed to catch {bug}"
    assert violation.scenario == f"{MUTATIONS[bug]} (bug={bug})"
    text = format_schedule(violation)
    assert text.startswith(f"schedule ({MUTATIONS[bug]} (bug={bug})):")
    # one numbered `<process>.<step>` line per step, in execution order
    steps = re.findall(r"^\s+(\d+)\. \w+\.\w+", text, re.MULTILINE)
    assert [int(s) for s in steps] == list(range(1, len(steps) + 1))
    assert steps, "schedule must list the interleaving"
    assert "\nviolated: " in text


def test_model_mutation_rejects_mismatched_scenario():
    with pytest.raises(ValueError):
        build_scenario("appender-cursor", bug="mirror-clobber")
    with pytest.raises(KeyError):
        build_scenario("no-such-scenario")


def test_seal_lock_mutation_schedule_shows_double_break():
    # the canonical counterexample: both sealers judge the same lock
    # stale before either breaks it
    v = find_violation(build_scenario("seal-lock", bug="unlink-lock-break"))
    assert v is not None
    assert v.invariant == "single-sealer"
    text = format_schedule(v)
    assert text.count("break_unlink_shared") == 2


@pytest.mark.slow
def test_model_deep_sweep_clean():
    """Full sweep (crash budget 2) over every scenario stays green —
    the `--deep` CI tier."""
    for name, violation in check_all(deep=True):
        assert violation is None, f"{name}: {format_schedule(violation)}"


@pytest.mark.slow
def test_model_deep_sweep_still_catches_mutations():
    for bug in sorted(MUTATIONS):
        assert check_mutation(bug, deep=True) is not None, bug


# ---------------------------------------------------------------------
# registry / docs drift
# ---------------------------------------------------------------------

_ID_RE = re.compile(r"\b(?:SP1|PL2|RL3|DL4|SG7)\d\d\b")


def test_rule_registry_matches_docs():
    """Every analyzer rule id named in the docs is registered, and
    every registered rule is documented — the catalog cannot rot."""
    with open(os.path.join(_REPO, "docs", "static_analysis.md")) as f:
        documented = set(_ID_RE.findall(f.read()))
    registered = set(RULES)
    assert documented - registered == set(), "docs name unknown rules"
    assert registered - documented == set(), "registered rules undocumented"


def test_registered_sg_rules_are_exactly_the_family():
    assert {r for r in RULES if r.startswith("SG")} == {
        "SG701", "SG702", "SG703", "SG704", "SG705", "SG706", "SG707",
    }
    assert len(ROLES) == 4


def test_fsck_repair_ids_match_docs():
    """FS4xx ids are fsck *repair* rules, not analyzer rules: the set
    in the fsck source must equal the set in the docs, and none may
    leak into the analyzer registry."""
    fs_re = re.compile(r"\bFS4\d\d\b")
    with open(os.path.join(
        _REPO, "hyperopt_tpu", "resilience", "fsck.py",
    )) as f:
        in_source = set(fs_re.findall(f.read()))
    in_docs = set()
    for doc in ("resilience.md", "storage.md"):
        with open(os.path.join(_REPO, "docs", doc)) as f:
            in_docs |= set(fs_re.findall(f.read()))
    assert in_source == in_docs
    assert in_source, "fsck repair rules must exist"
    assert not any(r.startswith("FS") for r in RULES)


# ---------------------------------------------------------------------
# CI surfaces: scripts/lint.py --json / timing budget, CLI targets
# ---------------------------------------------------------------------


def _run(args, timeout=300):
    return subprocess.run(
        args, capture_output=True, text=True, cwd=_REPO, env=_ENV,
        timeout=timeout,
    )


def test_scripts_lint_fast_timing_and_budget():
    """--fast prints per-pass wall times and finishes inside the
    60-second budget the docstring promises."""
    proc = _run([sys.executable, os.path.join("scripts", "lint.py"),
                 "--fast"])
    assert proc.returncode == 0, proc.stdout + proc.stderr
    m = re.search(r"^== timing: (.*) total=([\d.]+)s$",
                  proc.stdout, re.MULTILINE)
    assert m, proc.stdout
    keys = [kv.split("=")[0] for kv in m.group(1).split()]
    assert keys == ["race", "durability", "program", "protocol", "model"]
    assert float(m.group(2)) < 60.0, "--fast blew the 60s budget"


def test_scripts_lint_json_schema():
    """--json: stable sorted schema on stdout (empty on the clean
    tree), timing on stderr so the artifact stays parseable."""
    proc = _run([sys.executable, os.path.join("scripts", "lint.py"),
                 "--fast", "--json"])
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert json.loads(proc.stdout) == []
    assert "== timing:" in proc.stderr
    assert "== timing:" not in proc.stdout


def test_cli_protocol_target():
    proc = _run([sys.executable, "-m", "hyperopt_tpu.analysis",
                 "protocol"])
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "protocol_lint (SG7xx + model check)" in proc.stdout


def test_cli_protocol_target_json_on_seeded_violation(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text(textwrap.dedent("""
        def sweep(orphans):  # protocol: orphan-sweep
            for path in orphans:
                os.unlink(path)
    """))
    proc = _run([sys.executable, "-m", "hyperopt_tpu.analysis",
                 "protocol", str(bad), "--json"])
    rows = json.loads(proc.stdout)
    # exit code = error count: the seeded SG701 (the clean-tree model
    # check contributes zero)
    assert proc.returncode == 1, proc.stdout + proc.stderr
    assert [r["rule"] for r in rows] == ["SG701"]
    assert set(rows[0]) == {
        "rule", "severity", "file", "line", "message", "hint",
    }
    assert rows[0]["line"] == 4 and rows[0]["hint"]


def test_cli_infers_bare_py_file_as_race_plus_durability(tmp_path):
    bad = tmp_path / "bad.py"
    # one race violation (guarded field written without its lock) and
    # one durability violation (truncate-then-write of a live path)
    bad.write_text(textwrap.dedent("""
        import threading

        class C:
            def __init__(self):
                self.lock = threading.Lock()
                self.n = 0  # guarded-by: lock

            def bump(self):
                self.n += 1

        def save(path, data):
            with open(path, "w") as f:
                f.write(data)
    """))
    proc = _run([sys.executable, "-m", "hyperopt_tpu.analysis",
                 str(bad), "--json"])
    rows = json.loads(proc.stdout)
    fired = {r["rule"] for r in rows}
    assert "RL301" in fired and "DL401" in fired, rows
    assert proc.returncode == len(
        [r for r in rows if r["severity"] == "error"]
    )


def test_cli_infers_module_as_space_pass(tmp_path):
    mod = tmp_path / "my_space.py"
    mod.write_text(textwrap.dedent("""
        from hyperopt_tpu import hp

        space = {"x": hp.uniform("x", 0.0, 1.0)}
    """))
    proc = _run([sys.executable, "-m", "hyperopt_tpu.analysis",
                 str(mod).replace(".py", "") + ".py:space", "--json"])
    # a .py path with :attr is a space target, not file inference
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert json.loads(proc.stdout) == []


def test_cli_json_schema_stable_across_targets(tmp_path):
    """The --json schema is identical for every target: same keys,
    same ordering contract (sorted by file, line, rule)."""
    bad = tmp_path / "bad.py"
    bad.write_text(textwrap.dedent("""
        def sweep(orphans):  # protocol: orphan-sweep
            os.unlink(orphans)

        def save(path, data):
            with open(path, "w") as f:
                f.write(data)
    """))
    per_target = {}
    for target in ("protocol", "durability"):
        proc = _run([sys.executable, "-m", "hyperopt_tpu.analysis",
                     target, str(bad), "--json"])
        rows = json.loads(proc.stdout)
        assert rows, f"{target} found nothing"
        per_target[target] = rows
        for row in rows:
            assert list(row) == [
                "rule", "severity", "file", "line", "message", "hint",
            ]
    assert [r["rule"] for r in per_target["protocol"]] == ["SG701"]
    assert [r["rule"] for r in per_target["durability"]] == ["DL401"]
