"""Native fastqueue component tests: builds the C++ lib with g++, checks
parity with the Python implementations, lock exclusivity, and graceful
fallback."""

import ctypes
import os
import threading

import pytest

from hyperopt_tpu import native
from hyperopt_tpu.base import JOB_STATE_DONE, JOB_STATE_NEW, JOB_STATE_RUNNING
from hyperopt_tpu.parallel.file_trials import FileJobs


def have_toolchain():
    return native.load_fastqueue() is not None


pytestmark = pytest.mark.skipif(
    not have_toolchain(), reason="g++ toolchain unavailable"
)


def make_doc(tid, state):
    return {
        "tid": tid, "state": state, "spec": None,
        "result": {"status": "new"},
        "misc": {"tid": tid, "cmd": None, "idxs": {}, "vals": {}},
        "exp_key": None, "owner": None, "book_time": None, "refresh_time": None,
    }


def test_native_lib_builds_and_loads():
    lib = native.load_fastqueue()
    assert lib is not None
    assert hasattr(lib, "fq_count_states")


def test_count_states_matches_python(tmp_path):
    # the native scanner reads the legacy per-doc layout
    jobs = FileJobs(str(tmp_path), backend="doc")
    for tid, state in enumerate([0, 0, 2, 2, 2, 1, 4]):
        jobs.insert(make_doc(tid, state))
    res = native.count_states(os.path.join(str(tmp_path), "trials"))
    assert res is not None
    counts, n = res
    assert n == 7
    assert counts[JOB_STATE_NEW] == 2
    assert counts[JOB_STATE_DONE] == 3
    assert counts[JOB_STATE_RUNNING] == 1
    assert counts[4] == 1
    # FileJobs.count_states agrees (whichever path it took)
    assert jobs.count_states()[JOB_STATE_DONE] == 3


def test_list_state_sorted(tmp_path):
    jobs = FileJobs(str(tmp_path), backend="doc")
    for tid, state in [(5, 0), (2, 0), (9, 2), (1, 0)]:
        jobs.insert(make_doc(tid, state))
    tids = native.list_state(os.path.join(str(tmp_path), "trials"), JOB_STATE_NEW)
    assert tids == [1, 2, 5]


def test_try_lock_exclusive(tmp_path):
    lock = str(tmp_path / "t.lock")
    assert native.try_lock(lock, "w1") == 1
    assert native.try_lock(lock, "w2") == 0
    with open(lock) as f:
        assert f.read() == "w1"


def test_try_lock_race(tmp_path):
    lock = str(tmp_path / "race.lock")
    wins = []
    barrier = threading.Barrier(8)

    def go(i):
        barrier.wait()
        if native.try_lock(lock, f"w{i}") == 1:
            wins.append(i)

    threads = [threading.Thread(target=go, args=(i,)) for i in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert len(wins) == 1


def test_reserve_uses_native_and_agrees(tmp_path):
    jobs = FileJobs(str(tmp_path), backend="doc")
    for tid in range(5):
        jobs.insert(make_doc(tid, JOB_STATE_NEW))
    seen = set()
    while True:
        doc = jobs.reserve("worker")
        if doc is None:
            break
        seen.add(doc["tid"])
        assert doc["state"] == JOB_STATE_RUNNING
        assert doc["owner"] == "worker"
    assert seen == {0, 1, 2, 3, 4}


def test_unparsed_doc_falls_back(tmp_path):
    jobs = FileJobs(str(tmp_path), backend="doc")
    jobs.insert(make_doc(0, JOB_STATE_NEW))
    # hand-write a doc the textual scanner cannot parse (no "state": int)
    weird = os.path.join(str(tmp_path), "trials", "000000000099.json")
    with open(weird, "w") as f:
        f.write('{"tid": 99, "state"\n:\n0, "misc": {"tid": 99, "cmd": null, '
                '"idxs": {}, "vals": {}}, "result": {"status": "new"}, '
                '"spec": null, "exp_key": null, "owner": null, '
                '"book_time": null, "refresh_time": null}')
    # native count reports unparsed -> count_states falls back to exact
    counts = jobs.count_states()
    assert counts[JOB_STATE_NEW] == 2
