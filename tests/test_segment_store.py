"""SegmentStore cross-process safety regressions.

The segmented trial log promises multi-process-appender safety on a
shared filesystem (module docstring of
``hyperopt_tpu.parallel.segment_store``).  These tests pin the two
subtle pieces of that promise:

- an appender must never advance its replay cursor over bytes another
  process's ``O_APPEND`` write landed between its refresh and its own
  write (the gap would be skipped until the next compaction epoch);
- breaking a stale ``.seal.lock`` must not let two sealers run
  concurrently (the break goes through a rename only one process can
  win).
"""

import os
import time

from hyperopt_tpu.parallel import segment_store as sstore


def _doc(tid, state=0):
    return {"tid": tid, "state": state, "misc": {"tid": tid}}


class TestInterleavedAppenders:
    def test_append_does_not_skip_interleaved_appender_bytes(
        self, tmp_path, monkeypatch
    ):
        """Process A refreshes, process B appends, process A appends:
        B's record sits in [A's cursor, A's write start) and A must
        replay it on its next refresh instead of jumping its cursor
        past it forever."""
        a = sstore.SegmentStore(str(tmp_path), auto_compact=False)
        b = sstore.SegmentStore(str(tmp_path), auto_compact=False)
        a.append(_doc(0))
        b.refresh()

        real = sstore.journal_io.append_records
        fired = []

        def interleave(path, payloads, **kw):
            # B's append lands first, below A's — exactly the window
            # between A's in-lock refresh and A's own O_APPEND write
            if not fired:
                fired.append(True)
                b.append(_doc(1))
            return real(path, payloads, **kw)

        monkeypatch.setattr(
            sstore.journal_io, "append_records", interleave
        )
        a.append(_doc(2))
        monkeypatch.setattr(sstore.journal_io, "append_records", real)

        assert sorted(d["tid"] for d in a.all_docs()) == [0, 1, 2]
        assert a.count_states()[0] == 3
        # B (whose own cursor is contiguous) sees everything too
        assert sorted(d["tid"] for d in b.all_docs()) == [0, 1, 2]

    def test_contiguous_append_still_advances_the_cursor(self, tmp_path):
        """The common single-appender case keeps its O(0) refresh: the
        appender's own bytes are not re-read on the next refresh."""
        store = sstore.SegmentStore(str(tmp_path), auto_compact=False)
        store.append(_doc(0))
        active = store._manifest["active"]
        size = os.path.getsize(store.segment_path(active))
        assert store._offsets[active] == size
        assert store.refresh() == []  # nothing unseen


class TestStaleSealLock:
    def test_stale_lock_is_broken_and_seal_proceeds(self, tmp_path):
        store = sstore.SegmentStore(str(tmp_path), auto_compact=False)
        store.append(_doc(0))
        lock = os.path.join(store.dir, ".seal.lock")
        with open(lock, "w"):
            pass
        old = time.time() - 120.0
        os.utime(lock, (old, old))
        store.seal_active()
        assert store.sealed_entries()  # the seal landed
        # no residue: neither the shared lock nor the private rename
        # target survives the break
        leftovers = [
            n for n in os.listdir(store.dir)
            if n == ".seal.lock" or ".stale-" in n
        ]
        assert leftovers == []

    def test_losing_breaker_retries_instead_of_unlinking(
        self, tmp_path, monkeypatch
    ):
        """Two processes judge the lock stale; the rename loser must
        NOT remove the shared path (which the winner may have just
        re-created as its own live lock)."""
        store = sstore.SegmentStore(str(tmp_path), auto_compact=False)
        store.append(_doc(0))
        lock = os.path.join(store.dir, ".seal.lock")
        with open(lock, "w"):
            pass
        old = time.time() - 120.0
        os.utime(lock, (old, old))

        real_rename = os.rename

        def lose_the_race(src, dst, *a, **kw):
            if src == lock and ".stale-" in str(dst):
                # the other breaker renamed the stale lock first and
                # immediately re-acquired: simulate by freshening the
                # shared path (their new live lock)
                os.utime(lock, None)
                raise FileNotFoundError(src)
            return real_rename(src, dst, *a, **kw)

        monkeypatch.setattr(os, "rename", lose_the_race)
        assert store._seal_lock_acquire(timeout=0.2) is None
        # the winner's fresh lock is untouched
        assert os.path.exists(lock)
