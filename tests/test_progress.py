"""Progress-callback tests (reference parity: ``hyperopt/tests/test_progress.py``
asserts the callback context manager is entered and ``.update`` is invoked
once per finished trial; SURVEY.md §2 #20)."""

import contextlib

import numpy as np

from hyperopt_tpu import Trials, fmin, hp, rand
from hyperopt_tpu import progress


def test_no_progress_callback_handle_is_inert():
    with progress.no_progress_callback(initial=0, total=5) as handle:
        handle.update(3)  # no-op, must not raise
        assert handle.postfix is None
        handle.postfix = "best: 1.0"  # setter also inert


def test_tqdm_progress_callback_updates_and_postfix(capsys):
    with progress.tqdm_progress_callback(initial=0, total=4) as handle:
        handle.update(2)
        handle.postfix = "best loss: 0.5"
        assert "best loss: 0.5" in str(handle.postfix)
        handle.postfix = None  # clears without raising


def test_fmin_invokes_custom_progress_callback(monkeypatch):
    """fmin(show_progressbar=True) must route through
    ``progress.default_callback``, update once per finished trial, and set
    a best-loss postfix."""
    calls = {"entered": 0, "updates": [], "postfix": []}

    class Handle:
        def update(self, n):
            calls["updates"].append(n)

        @property
        def postfix(self):
            return None

        @postfix.setter
        def postfix(self, value):
            calls["postfix"].append(value)

    @contextlib.contextmanager
    def recording_callback(initial, total):
        calls["entered"] += 1
        calls["total"] = total
        yield Handle()

    monkeypatch.setattr(progress, "default_callback", recording_callback)

    fmin(
        fn=lambda x: x**2,
        space=hp.uniform("x", -1, 1),
        algo=rand.suggest,
        max_evals=7,
        trials=Trials(),
        rstate=np.random.default_rng(0),
        show_progressbar=True,
    )
    assert calls["entered"] == 1
    assert calls["total"] == 7
    assert sum(calls["updates"]) == 7
    assert calls["postfix"], "best-loss postfix never set"


def test_dummy_tqdm_file_fileno_contract():
    """fileno() mirrors the other methods' defensiveness: real fd when the
    wrapped object has one, io.UnsupportedOperation (not AttributeError)
    when it doesn't (ADVICE r4)."""
    import io
    import sys

    import pytest

    from hyperopt_tpu.std_out_err_redirect_tqdm import DummyTqdmFile

    assert DummyTqdmFile(sys.__stdout__).fileno() == sys.__stdout__.fileno()
    with pytest.raises(io.UnsupportedOperation):
        DummyTqdmFile(object()).fileno()
