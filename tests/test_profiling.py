"""Tests for the roofline device-observability layer
(hyperopt_tpu.profiling + observability.DeviceStats).

Covers: roofline math units (binding-ceiling selection, GB/s
arithmetic), cost-model vs XLA ``cost_analysis()`` agreement on CPU,
observer wiring (one record per fused dispatch, compile tagging,
consume-once last-record), DeviceStats aggregation and Prometheus
exposition shape, service integration (device stats on /metrics,
roofline attrs on ``device.dispatch`` spans, batched fan-out consistent
with the tracing pro-rata convention), the bounded jax.profiler
capture, the bench null-with-reason headline contract, and the
race-lint registration satellite.
"""

import json
import os
import threading

import numpy as np
import pytest

from hyperopt_tpu import Trials, hp, profiling, tracing
from hyperopt_tpu.base import JOB_STATE_DONE, STATUS_OK, Domain
from hyperopt_tpu.observability import DeviceStats, render_prometheus


def _mixed_space(tag="x"):
    return {
        f"lr_{tag}": hp.loguniform(f"lr_{tag}", np.log(1e-4), np.log(1.0)),
        f"mom_{tag}": hp.uniform(f"mom_{tag}", 0.0, 1.0),
        f"c_{tag}": hp.choice(f"c_{tag}", ["a", "b", "c"]),
    }


def _grown_trials(domain, n=30, seed=0):
    """n completed trials so suggests reach the device plane."""
    from hyperopt_tpu.algos import tpe

    rng = np.random.default_rng(seed)
    trials = Trials()
    docs = tpe.suggest(list(range(n)), domain, trials, seed)
    for d in docs:
        d["state"] = JOB_STATE_DONE
        d["result"] = {
            "status": STATUS_OK, "loss": float(rng.standard_normal()),
        }
    trials._insert_trial_docs(docs)
    trials.refresh()
    return trials


def _complete(trials, docs, rng):
    for d in docs:
        d["state"] = JOB_STATE_DONE
        d["result"] = {
            "status": STATUS_OK, "loss": float(rng.standard_normal()),
        }
    trials._insert_trial_docs(docs)
    trials.refresh()


# ---------------------------------------------------------------------
# roofline math units
# ---------------------------------------------------------------------


class TestRoofline:
    def test_ridge_is_peaks_ratio(self):
        peaks = profiling.platform_peaks("tpu")
        assert peaks["ridge_ai"] == pytest.approx(
            peaks["peak_tflops"] * 1e12 / (peaks["peak_hbm_GBps"] * 1e9)
        )

    def test_low_intensity_binds_on_bandwidth(self):
        peaks = profiling.platform_peaks("tpu")
        # AI = 1 flop/byte, far below the ~240 ridge
        r = profiling.roofline(1e9, 1e9, 0.01, peaks)
        assert r["binding_ceiling"] == "hbm_bw"
        # bandwidth-bound: the binding pct IS the bandwidth pct
        assert r["roofline_pct"] == r["roofline_pct_bw"]
        assert r["roofline_pct"] != r["roofline_pct_mxu"]

    def test_high_intensity_binds_on_flops(self):
        peaks = profiling.platform_peaks("tpu")
        r = profiling.roofline(1e12, 1e6, 0.01, peaks)  # AI = 1e6
        assert r["binding_ceiling"] == "flops"
        assert r["roofline_pct"] == r["roofline_pct_mxu"]

    def test_gbps_arithmetic(self):
        peaks = profiling.platform_peaks("tpu")
        # exactly 1% of 819 GB/s moved in 1 s
        r = profiling.roofline(1.0, 8.19e9, 1.0, peaks)
        assert r["achieved_GBps"] == pytest.approx(8.19)
        assert r["roofline_pct_bw"] == pytest.approx(1.0)
        assert r["binding_ceiling"] == "hbm_bw"
        assert r["roofline_pct"] == pytest.approx(1.0)

    def test_bandwidth_pct_equals_attainable_flops_pct(self):
        # identity: below the ridge, achieved/attainable FLOP/s ==
        # achieved/peak GB/s — the two formulations must agree
        peaks = profiling.platform_peaks("tpu")
        flops, nbytes, secs = 3e9, 1e9, 0.004
        r = profiling.roofline(flops, nbytes, secs, peaks)
        assert r["binding_ceiling"] == "hbm_bw"
        ai = flops / nbytes
        attainable_tflops = ai * peaks["peak_hbm_GBps"] * 1e9 / 1e12
        assert r["roofline_pct"] == pytest.approx(
            100.0 * r["achieved_tflops"] / attainable_tflops
        )

    def test_unmeasurable_is_null_never_zero(self):
        peaks = profiling.platform_peaks("cpu")
        for args in ((1e9, 1e9, 0.0), (0.0, 0.0, 1.0)):
            r = profiling.roofline(*args, peaks)
            assert r["binding_ceiling"] is None
            assert r["roofline_pct"] is None
            assert r["achieved_GBps"] is None

    def test_env_override(self, monkeypatch):
        monkeypatch.setenv("HYPEROPT_TPU_PEAK_TFLOPS", "100.0")
        monkeypatch.setenv("HYPEROPT_TPU_PEAK_HBM_GBPS", "1000.0")
        peaks = profiling.platform_peaks("tpu")
        assert peaks["peak_tflops"] == 100.0
        assert peaks["peak_hbm_GBps"] == 1000.0
        assert peaks["source"] == "env_override"
        assert peaks["ridge_ai"] == pytest.approx(100.0)

    def test_cpu_peaks_are_flagged_nominal(self):
        assert profiling.platform_peaks("cpu")["source"] == "nominal_cpu"


# ---------------------------------------------------------------------
# cost model
# ---------------------------------------------------------------------


class TestCostModel:
    def _requests(self, n_cand=512, tag="cm"):
        from hyperopt_tpu.algos import tpe

        domain = Domain(lambda c: 0.0, _mixed_space(tag))
        trials = _grown_trials(domain, n=30)
        prep = tpe.suggest_prepare(
            [1000], domain, trials, 7, n_EI_candidates=n_cand
        )
        assert prep is not None
        return prep[0]

    def test_analytical_vs_xla_cost_analysis(self):
        """The analytical model must agree with XLA's own cost analysis
        of the same program within loose factors (the model counts the
        dominant scorer terms; XLA counts every op pre-fusion)."""
        reqs = self._requests(n_cand=512)
        ana = profiling.analytical_cost(reqs)
        xla = profiling.xla_cost(reqs)
        if xla is None:
            pytest.skip("backend exposes no cost_analysis")
        assert 0.2 < ana["flops"] / xla["flops"] < 5.0, (ana, xla)
        assert 0.02 < ana["bytes"] / xla["bytes"] < 5.0, (ana, xla)

    def test_cost_scales_with_candidates(self):
        reqs1 = self._requests(n_cand=256, tag="s1")
        reqs2 = self._requests(n_cand=1024, tag="s2")
        a1 = profiling.analytical_cost(reqs1)
        a2 = profiling.analytical_cost(reqs2)
        assert a2["flops"] > 2.0 * a1["flops"]
        assert a2["bytes"] > 1.5 * a1["bytes"]

    def test_signature_key_shape(self):
        reqs = self._requests(n_cand=256, tag="sk")
        key = profiling.signature_key(reqs)
        assert key.startswith("capt")
        assert "cont[" in key and "idx[" in key
        assert "c256" in key

    def test_mxu_flops_subset(self):
        reqs = self._requests(n_cand=256, tag="mx")
        ana = profiling.analytical_cost(reqs)
        assert 0 < ana["mxu_flops"] < ana["flops"]


# ---------------------------------------------------------------------
# observer wiring
# ---------------------------------------------------------------------


class TestObserverWiring:
    def test_one_record_per_dispatch_and_uninstall(self):
        from hyperopt_tpu.algos import tpe, tpe_device

        domain = Domain(lambda c: 0.0, _mixed_space("ow"))
        trials = _grown_trials(domain, n=30)
        rng = np.random.default_rng(3)
        stats = DeviceStats()
        prof = profiling.DeviceProfiler(stats=stats)
        n_before = len(tpe_device._suggest_observers)
        with prof:
            assert len(tpe_device._suggest_observers) == n_before + 1
            for i in range(4):
                docs = tpe.suggest([500 + i], domain, trials, i + 1)
                _complete(trials, docs, rng)
        # one fused dispatch per suggest -> one record each
        assert stats.n_dispatches == 4
        assert len(tpe_device._suggest_observers) == n_before
        # uninstalled: further dispatches record nothing
        tpe.suggest([900], domain, trials, 99)
        assert stats.n_dispatches == 4

    def test_last_record_consumed_once(self):
        from hyperopt_tpu.algos import tpe

        domain = Domain(lambda c: 0.0, _mixed_space("lr"))
        trials = _grown_trials(domain, n=30)
        profiling.last_dispatch_record()  # drain any prior state
        with profiling.DeviceProfiler(stats=DeviceStats()):
            tpe.suggest([600], domain, trials, 1)
            rec = profiling.last_dispatch_record()
            assert rec is not None
            assert rec["binding_ceiling"] is not None
            assert rec["roofline_pct"] is not None
            assert rec["device_s"] > 0
            assert rec["hbm_bytes"] > 0
            assert rec["live_bytes"] > 0
            # consumed: a second read must not see a stale record
            assert profiling.last_dispatch_record() is None

    def test_compile_tagging(self):
        """The first dispatch of a brand-new signature carries the XLA
        trace and is tagged ``compiled``; the steady state is not, and
        steady-state means exclude the compile-polluted timing."""
        from hyperopt_tpu.algos import tpe

        # a space shape unique to this test -> guaranteed fresh trace
        space = {
            "a_ct": hp.uniform("a_ct", 0.0, 1.0),
            "b_ct": hp.uniform("b_ct", 2.0, 3.0),
        }
        domain = Domain(lambda c: 0.0, space)
        trials = _grown_trials(domain, n=30)
        rng = np.random.default_rng(5)
        stats = DeviceStats()
        with profiling.DeviceProfiler(stats=stats):
            recs = []
            for i in range(3):
                # fresh ids, history NOT grown: one signature throughout
                tpe.suggest([700 + i], domain, trials, i + 1,
                            n_EI_candidates=777)
                recs.append(profiling.last_dispatch_record())
        assert recs[0]["compiled"] is True
        assert recs[1]["compiled"] is False
        assert recs[2]["compiled"] is False
        summ = stats.summary()
        assert summ["n_compile_dispatches"] == 1
        row = summ["signatures"][0]
        assert row["steady"] is True
        assert row["n_compile_dispatches"] == 1
        # steady mean excludes the compile-carrying dispatch: it must
        # sit far below the compile time
        assert row["device_ms_mean"] * 1e-3 < recs[0]["device_s"] / 2


# ---------------------------------------------------------------------
# DeviceStats aggregation + exposition
# ---------------------------------------------------------------------


def _rec(sig="s", device_s=0.01, ceiling="hbm_bw", pct=10.0,
         compiled=False, live=100, n_requests=1):
    return {
        "sig": sig, "n_requests": n_requests, "device_s": device_s,
        "launch_s": device_s / 2, "wait_s": 0.0,
        "readback_s": device_s / 2, "flops": 1e6, "mxu_flops": 5e5,
        "hbm_bytes": 1e6, "live_bytes": live, "cost_source": "analytical",
        "compiled": compiled, "achieved_tflops": 1e-4,
        "achieved_GBps": 0.1, "ai_flops_per_byte": 1.0,
        "ridge_ai": 240.0, "binding_ceiling": ceiling,
        "roofline_pct": pct, "roofline_pct_mxu": pct / 2,
        "roofline_pct_bw": pct,
    }


class TestDeviceStats:
    def test_aggregation_and_compile_exclusion(self):
        st = DeviceStats()
        st.record_dispatch(_rec(pct=50.0, compiled=True, device_s=2.0))
        st.record_dispatch(_rec(pct=10.0))
        st.record_dispatch(_rec(pct=20.0))
        st.record_dispatch(_rec(sig="t", ceiling="flops", pct=30.0))
        s = st.summary()
        assert s["n_dispatches"] == 4
        assert s["n_compile_dispatches"] == 1
        # ceiling counts include compiled (AI is timing-independent)
        assert s["binding_ceiling_counts"] == {"flops": 1, "hbm_bw": 3}
        # pct means exclude the compiled record
        assert s["roofline_pct_mean"]["hbm_bw"] == pytest.approx(15.0)
        assert s["roofline_pct_mean"]["flops"] == pytest.approx(30.0)

    def test_signature_table_prefers_steady(self):
        st = DeviceStats()
        st.record_dispatch(_rec(device_s=5.0, pct=0.001, compiled=True))
        st.record_dispatch(_rec(device_s=0.01, pct=25.0))
        row = st.signature_table()[0]
        assert row["steady"] is True
        assert row["device_ms_mean"] == pytest.approx(10.0)
        assert row["roofline_pct"] == 25.0

    def test_compile_only_signature_still_attributed(self):
        st = DeviceStats()
        st.record_dispatch(_rec(device_s=5.0, pct=0.5, compiled=True))
        row = st.signature_table()[0]
        assert row["steady"] is False
        assert row["binding_ceiling"] == "hbm_bw"
        assert row["roofline_pct"] == 0.5

    def test_memory_highwater(self):
        st = DeviceStats()
        st.record_dispatch(_rec(live=100))
        st.record_dispatch(_rec(live=5000))
        st.record_dispatch(_rec(live=200))
        st.set_backend_peak_bytes(123456)
        st.set_backend_peak_bytes(999)  # lower: must not regress
        mem = st.summary()["memory"]
        assert mem["live_buffer_highwater_bytes"] == 5000
        assert mem["backend_peak_bytes"] == 123456

    def test_duty_cycle_clamped(self):
        st = DeviceStats()
        st.record_dispatch(_rec(device_s=1e6))  # absurd busy interval
        assert st.duty_cycle() == 1.0

    def test_signature_cap_counts_drops(self):
        st = DeviceStats()
        for i in range(DeviceStats.MAX_SIGNATURES + 5):
            st.record_dispatch(_rec(sig=f"sig{i}"))
        s = st.summary()
        assert len(s["signatures"]) == DeviceStats.MAX_SIGNATURES
        assert s["signature_drops"] == 5
        # totals still count every dispatch
        assert s["n_dispatches"] == DeviceStats.MAX_SIGNATURES + 5

    def test_prometheus_exposition_shape(self):
        st = DeviceStats()
        st.record_dispatch(_rec(pct=12.5))
        st.record_dispatch(_rec(sig="t", ceiling="flops", pct=2.0))
        text = render_prometheus(device=st)
        for metric in (
            "hyperopt_device_dispatches_total",
            "hyperopt_device_busy_seconds_total",
            "hyperopt_device_duty_cycle",
            "hyperopt_device_hbm_bytes_total",
            "hyperopt_device_flops_total",
            "hyperopt_device_memory_highwater_bytes",
        ):
            assert f"# TYPE {metric}" in text, metric
        assert 'hyperopt_device_roofline_pct{ceiling="hbm_bw"} 12.5' in text
        assert 'hyperopt_device_roofline_pct{ceiling="flops"} 2.0' in text
        assert (
            'hyperopt_device_binding_dispatches_total{ceiling="hbm_bw"} 1'
            in text
        )
        assert (
            'hyperopt_device_memory_highwater_bytes{kind="live_buffers"}'
            in text
        )


# ---------------------------------------------------------------------
# service integration
# ---------------------------------------------------------------------


class TestServiceIntegration:
    def _run_service(self, tmp_path, concurrent=True, n_studies=2,
                     batch_window=0.004):
        from hyperopt_tpu.service.core import OptimizationService

        tracer = tracing.Tracer(
            path=str(tmp_path / "trace.jsonl"), sample=1.0
        )
        svc = OptimizationService(
            tracer=tracer, batch_window=batch_window
        )
        try:
            space = {
                "x_si": hp.uniform("x_si", -5, 5),
                "c_si": hp.choice("c_si", [1, 2]),
            }
            rng = np.random.default_rng(0)
            sids = [f"s{i}" for i in range(1, n_studies + 1)]
            for sid in sids:
                svc.create_study(
                    sid, space, seed=3, algo="tpe",
                    algo_params={"n_startup_jobs": 2},
                )
                for _ in range(4):  # past startup -> device plane
                    tr = svc.suggest(sid)
                    svc.report(
                        sid, tr[0]["tid"], loss=float(rng.random())
                    )
            if concurrent:
                barrier = threading.Barrier(len(sids))

                def one(sid):
                    barrier.wait()
                    svc.suggest(sid)

                ts = [
                    threading.Thread(target=one, args=(sid,))
                    for sid in sids
                ]
                for t in ts:
                    t.start()
                for t in ts:
                    t.join()
            metrics = svc.metrics_text()
            status = svc.service_status()
            dstats = svc.device_stats.summary()
            sstats = svc.stats.summary()
        finally:
            svc.close()
        recs, torn = tracing.read_trace_log(str(tmp_path / "trace.jsonl"))
        assert torn == 0
        return metrics, status, dstats, sstats, recs

    def test_device_stats_on_metrics_and_status(self, tmp_path):
        metrics, status, dstats, sstats, _ = self._run_service(tmp_path)
        assert "hyperopt_device_duty_cycle" in metrics
        assert "hyperopt_device_hbm_bytes_total" in metrics
        assert "hyperopt_device_roofline_pct" in metrics
        assert "device" in status
        # every scheduler dispatch was observed by the profiler
        assert dstats["n_dispatches"] == sstats["n_dispatches"] > 0
        assert dstats["memory"]["live_buffer_highwater_bytes"] > 0

    def test_dispatch_spans_carry_roofline_attrs(self, tmp_path):
        _, _, _, _, recs = self._run_service(tmp_path)
        dispatch_spans = [
            sp for r in recs for sp in r["spans"]
            if sp["name"] == "device.dispatch"
        ]
        assert dispatch_spans
        for sp in dispatch_spans:
            attrs = sp.get("attrs") or {}
            assert attrs.get("ceiling") in ("hbm_bw", "flops"), attrs
            assert attrs.get("roofline_pct") is not None
            assert attrs.get("achieved_GBps") is not None
            assert attrs.get("hbm_bytes", 0) > 0
            assert "compiled" in attrs

    def test_batched_fanout_consistent_with_pro_rata(self, tmp_path):
        """A coalesced batch fans the SAME roofline attrs to every
        member's device.dispatch span, and the tracing pro-rata
        convention still holds: pro_rata_s * batch_size == the shared
        span duration."""
        batched = []
        for attempt in range(3):  # coalescing is timing-dependent
            _, _, _, _, recs = self._run_service(
                tmp_path, concurrent=True, n_studies=4,
                batch_window=0.05,
            )
            batched = [
                sp for r in recs for sp in r["spans"]
                if sp["name"] == "device.dispatch"
                and (sp.get("attrs") or {}).get("batch_size", 1) > 1
            ]
            if batched:
                break
        if not batched:
            pytest.skip("no batch coalesced in 3 attempts")
        by_bytes = {}
        for sp in batched:
            attrs = sp["attrs"]
            assert attrs["pro_rata_s"] * attrs["batch_size"] == (
                pytest.approx(sp["dur_s"], abs=5e-3)
            )
            by_bytes.setdefault(
                round(sp["t0_s"], 1), set()
            ).add((attrs["hbm_bytes"], attrs["ceiling"]))
        # batch mates share one dispatch record -> identical attrs
        for grp in by_bytes.values():
            assert len(grp) == 1

    def test_close_uninstalls_profiler(self, tmp_path):
        from hyperopt_tpu.algos import tpe_device
        from hyperopt_tpu.service.core import OptimizationService

        svc = OptimizationService()
        obs = svc.device_profiler._observe
        assert obs in tpe_device._suggest_observers
        svc.close()
        assert obs not in tpe_device._suggest_observers


# ---------------------------------------------------------------------
# bounded jax.profiler capture
# ---------------------------------------------------------------------


class TestProfileCapture:
    def test_capture_is_bounded(self, tmp_path):
        from hyperopt_tpu.algos import tpe

        domain = Domain(lambda c: 0.0, _mixed_space("pc"))
        trials = _grown_trials(domain, n=30)
        cap = profiling.ProfileCapture(
            str(tmp_path / "prof"), max_dispatches=2
        )
        with cap:
            for i in range(4):
                tpe.suggest([800 + i], domain, trials, i + 1)
        s = cap.summary()
        if not s["started"]:
            pytest.skip("jax.profiler unavailable on this backend")
        assert s["stopped"] is True
        assert s["n_captured"] == 2
        # the capture actually landed on disk
        captured = [
            os.path.join(dp, f)
            for dp, _, fs in os.walk(tmp_path / "prof") for f in fs
        ]
        assert captured

    def test_zero_budget_never_arms(self, tmp_path):
        from hyperopt_tpu.algos import tpe_device

        cap = profiling.ProfileCapture(str(tmp_path), max_dispatches=0)
        n = len(tpe_device._suggest_observers)
        cap.install()
        assert len(tpe_device._suggest_observers) == n
        cap.uninstall()


# ---------------------------------------------------------------------
# bench headline null contract (the zeroed-headline fix)
# ---------------------------------------------------------------------


class TestBenchNullContract:
    def _bench(self):
        import importlib.util

        root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        spec = importlib.util.spec_from_file_location(
            "bench_for_test", os.path.join(root, "bench.py")
        )
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        return mod

    def test_unavailable_rate_yields_null_plus_reason(self):
        bench = self._bench()
        cost = {"flops": 1e9, "bytes": 1e8, "mxu_flops": 5e8}
        out = bench.device_headline_fields(cost, 1e8, 0.0, "tpu", "pallas")
        for key in (
            "value_unused", "achieved_tflops", "achieved_GBps", "mfu_pct",
            "binding_ceiling", "roofline_pct", "roofline_pct_bw",
        ):
            if key == "value_unused":
                continue
            assert out[key] is None, key
        assert out["unmeasured_reason"]
        # NEVER a 0.0 placeholder
        assert 0.0 not in (
            out["achieved_tflops"], out["mfu_pct"], out["achieved_GBps"],
        )

    def test_measured_rate_yields_roofline_fields(self):
        bench = self._bench()
        cost = {"flops": 5.4e9, "bytes": 1.2e6, "mxu_flops": 3.2e9}
        out = bench.device_headline_fields(
            cost, 3.28e8, 2.3e11, "tpu", "pallas"
        )
        assert out["unmeasured_reason"] is None
        assert out["achieved_tflops"] > 0
        assert out["achieved_GBps"] > 0
        assert out["binding_ceiling"] in ("hbm_bw", "flops")
        assert out["roofline_pct"] > 0
        assert out["roofline_pct_bw"] > 0
        assert out["mfu_pct"] > 0
        assert out["mfu_pct_reason"] is None

    def test_cpu_mfu_is_null_with_reason_roofline_is_not(self):
        bench = self._bench()
        cost = {"flops": 1e9, "bytes": 1e8, "mxu_flops": 5e8}
        out = bench.device_headline_fields(cost, 1e8, 1e10, "cpu", "xla")
        assert out["mfu_pct"] is None
        assert out["mfu_pct_reason"]
        assert out["binding_ceiling"] is not None
        assert out["roofline_pct"] is not None
        assert out["peaks"]["source"] == "nominal_cpu"


# ---------------------------------------------------------------------
# race lint registration (satellite)
# ---------------------------------------------------------------------


def test_profiling_registered_and_race_clean():
    from hyperopt_tpu.analysis import discover_race_files, lint_races

    paths = [p for p in discover_race_files() if p.endswith("profiling.py")]
    assert paths, "profiling.py must be race-linted"
    diags = lint_races(paths=paths)
    assert not diags, [str(d) for d in diags]
    src = open(paths[0]).read()
    assert "# guarded-by: _lock" in src
