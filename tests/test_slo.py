"""SLO guardrails (ISSUE 9).

Covers the tentpole contracts:

- multi-window burn-rate arithmetic over histogram-bucket deltas (a
  window histogram is the elementwise difference of two snapshots);
- each SL6xx rule fires on its degenerate signal and ONLY then
  (seeded fixtures single-sourced from scripts/slo_report.FIXTURES);
- the multi-window discipline: a hot fast window with a cold slow
  window does NOT breach (no paging on one bad minute after a clean
  hour);
- breach transitions: ok → breach increments ``breaches_total`` and
  dumps exactly one flight-recorder bundle; recovery clears status
  without re-dumping;
- the flight recorder: bounded trace ring fed by Tracer.finish
  regardless of head-sampling, pull providers read only at dump time,
  CRC-per-record bundle round-trip, torn-record detection, pruning;
- storage-plane telemetry (StoreStats) reconciles against trial
  counts on a driven service, and the warm/cold latency split
  attributes first-touch compiles;
- the service surfaces: ``/v1/alerts`` over HTTP, client.alerts(),
  slo/store/build-info families on ``/metrics``, crash-hook dump.
"""

import json
import os
import sys
import threading
import time

import pytest

from hyperopt_tpu import hp, slo, tracing
from hyperopt_tpu.observability import (
    DeviceStats,
    ServiceStats,
    StoreStats,
    quantile_from_counts,
)
from hyperopt_tpu.tracing import Tracer

sys.path.insert(
    0,
    os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "scripts",
    ),
)

SPACE = {
    "x": hp.uniform("x", -5, 5),
    "c": hp.choice("c", ["a", "b"]),
}
AP = {"n_startup_jobs": 1, "n_EI_candidates": 8}


def _drain(svc):
    try:
        svc.close(timeout=10.0)
    except Exception:
        pass


class _Clock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


def _engine(clock, recorder=None, **kwargs):
    env = {
        "service": ServiceStats(),
        "device": DeviceStats(),
        "store": StoreStats(),
    }
    eng = slo.SloEngine(
        service_stats=env["service"],
        device_stats=env["device"],
        store_stats=env["store"],
        recorder=recorder,
        time_fn=clock,
        snapshot_interval=1.0,
        **kwargs,
    )
    env["engine"] = eng
    return env


# ---------------------------------------------------------------------
# window arithmetic
# ---------------------------------------------------------------------


class TestWindows:
    def test_quantile_from_counts_matches_histogram(self):
        from hyperopt_tpu.observability import LatencyHistogram

        h = LatencyHistogram(buckets=(0.01, 0.1, 1.0))
        for v in (0.005, 0.05, 0.5, 5.0):
            h.observe(v)
        s = h.state()
        for q in (0.5, 0.9, 0.99):
            assert quantile_from_counts(
                s["edges"], s["counts"], q
            ) == h.quantile(q)

    def test_window_delta_sees_only_recent_observations(self):
        clock = _Clock()
        env = _engine(clock)
        eng, ss = env["engine"], env["service"]
        for _ in range(10):
            ss.record_request("suggest", seconds=0.01, study="s")
        clock.t = 100.0
        eng.tick()  # snapshot carrying the 10 old observations
        for _ in range(5):
            ss.record_request("suggest", seconds=0.02, study="s")
        clock.t = 150.0
        cur = eng._capture()
        with eng._lock:
            snaps = list(eng._snapshots)
        # nominal 50 s: the t=100 snapshot is exactly old enough, so
        # the window excludes the 10 older observations
        win = eng._window(cur, 50.0, snaps)
        assert win.hist("suggest_warm")["total"] == 5
        # nominal longer than the snapshot spacing allows: the window
        # extends to the earliest snapshot (more coverage, never empty)
        full = eng._window(cur, 10_000.0, snaps)
        assert full.hist("suggest_warm")["total"] == 15

    def test_count_above_is_exact_at_bucket_edges(self):
        state = {"edges": (0.1, 1.0, 2.5), "counts": [3, 2, 1, 4]}
        assert slo._count_above(state, 2.5) == 4
        assert slo._count_above(state, 1.0) == 5
        assert slo._count_above(state, 0.1) == 7

    def test_count_above_non_edge_bound_undercounts(self):
        """A bound inside a bucket excludes that bucket entirely — the
        conservative direction: a custom objective off a bucket edge
        must never page on observations that may be under it."""
        state = {"edges": (0.1, 1.0, 2.5), "counts": [3, 2, 1, 4]}
        # bound 1.5 sits inside (1.0, 2.5]: that bucket's 1 observation
        # is excluded (it may be 1.2 — under the bound); only buckets
        # entirely above count
        assert slo._count_above(state, 1.5) == 4
        assert slo._count_above(state, 0.5) == 5

    def test_idle_device_burn_is_finite(self):
        """Duty 0 is the WORST SL604 breach: the exported burn must be
        a finite >= 1 number an external alert can fire on, not NaN."""
        win = slo._Window(100.0, 300.0, {"busy_s": 0.0, "dispatches": 10},
                          {})
        burn, duty, _ = slo.DutyCycleRule().eval_window(win, {})
        assert duty == 0.0
        assert burn == 1e6
        assert slo._round6(burn) == 1e6


# ---------------------------------------------------------------------
# rules — seeded fixtures single-sourced from the acceptance script
# ---------------------------------------------------------------------


class TestRuleFixtures:
    def test_every_fixture_fires_exactly_its_intended_rule(self, tmp_path):
        import slo_report

        for rule_id, name, inject, baseline_kwargs in slo_report.FIXTURES:
            rec = slo_report.run_fixture(
                rule_id, name, inject,
                str(tmp_path / rule_id), baseline_kwargs=baseline_kwargs,
            )
            assert rec["ok"], (rule_id, rec)
            assert rec["pre_breaching"] == []
            assert rec["breaching"] == [rule_id]
            assert rec["bundle"]["ok"]
            assert rec["bundle"]["breaching_trace_ids_present"]

    def test_healthy_traffic_breaches_nothing(self):
        clock = _Clock()
        env = _engine(clock)
        for _ in range(50):
            env["service"].record_request(
                "suggest", seconds=0.02, study="s"
            )
            env["store"].record_fsync(0.001, kind="doc", nbytes=100)
        env["device"].record_dispatch({
            "sig": "x", "device_s": 8.0, "n_requests": 8,
            "binding_ceiling": "hbm_bw", "roofline_pct": 10.0,
            "hbm_bytes": 1e6, "flops": 1e6, "live_bytes": 10,
            "compiled": False,
        })
        clock.t = 60.0
        env["engine"].tick()
        rows = env["engine"].evaluate(force=True)
        assert all(r["status"] != "breach" for r in rows), rows
        assert env["engine"].current_breaching() == []

    def test_no_data_never_breaches(self):
        clock = _Clock()
        env = _engine(clock)
        clock.t = 60.0
        rows = env["engine"].evaluate(force=True)
        # an idle server: latency/duty/fsync rules lack data, the
        # zero-tolerance and rate rules read clean
        by_rule = {r["rule"]: r for r in rows}
        assert by_rule["SL601"]["status"] == "no_data"
        assert by_rule["SL604"]["status"] == "no_data"
        assert all(r["status"] != "breach" for r in rows)


# ---------------------------------------------------------------------
# multi-window discipline + transitions
# ---------------------------------------------------------------------


class TestMultiWindow:
    def test_hot_fast_window_with_cold_slow_window_does_not_breach(self):
        clock = _Clock()
        env = _engine(clock)
        eng, ss = env["engine"], env["service"]
        # a clean hour: 1000 served requests
        for _ in range(1000):
            ss.record_request("suggest", study="s")
        clock.t = 3000.0
        eng.tick()  # snapshot: the fast window will start after this
        # one bad minute: 20 rejections against 20 serves
        for _ in range(20):
            ss.record_request("suggest", study="s")
            ss.record_rejection("suggest")
        clock.t = 3300.0
        rows = {r["rule"]: r for r in eng.evaluate(force=True)}
        r = rows["SL603"]
        # fast window burns (0.5/0.05 = 10) but the slow window holds
        # (40/1040 over 5% budget < 1) — no breach, no page
        assert r["burn_fast"] >= 1.0
        assert r["burn_slow"] < 1.0
        assert r["status"] == "ok"
        assert eng.current_breaching() == []

    def test_breach_transition_counts_and_recovers(self, tmp_path):
        clock = _Clock()
        recorder = slo.FlightRecorder(bundle_dir=str(tmp_path))
        env = _engine(clock, recorder=recorder)
        eng, ss = env["engine"], env["service"]
        for _ in range(20):
            ss.record_request("suggest", study="s")
            ss.record_rejection("suggest")
        clock.t = 50.0
        eng.tick()
        assert eng.current_breaching() == ["SL603"]
        rows = {r["rule"]: r for r in eng.evaluate(force=True)}
        assert rows["SL603"]["breaches_total"] == 1
        assert recorder.summary()["n_dumps"] == 1  # one dump per transition
        # still breaching on the next tick: no second dump
        clock.t = 55.0
        eng.tick()
        assert recorder.summary()["n_dumps"] == 1
        # recovery: an hour of clean traffic pushes both windows green
        for _ in range(5000):
            ss.record_request("suggest", study="s")
        clock.t = 50.0 + 3700.0
        eng.tick()
        assert eng.current_breaching() == []
        rows = {r["rule"]: r for r in eng.evaluate(force=True)}
        assert rows["SL603"]["status"] == "ok"
        assert rows["SL603"]["breaches_total"] == 1  # transitions, not ticks

    def test_read_route_errors_do_not_inflate_sl603(self):
        """A flaky read-only endpoint (500s on /v1/status) must not
        page the mutating-route error SLO: numerator and denominator
        cover the same (mutating) population."""
        clock = _Clock()
        env = _engine(clock)
        eng, ss = env["engine"], env["service"]
        for _ in range(20):
            ss.record_request("suggest", study="s")
            ss.record_error("other")  # read-route 500s
        clock.t = 50.0
        rows = {r["rule"]: r for r in eng.evaluate(force=True)}
        assert rows["SL603"]["status"] == "ok"
        assert rows["SL603"]["burn_fast"] == 0.0
        # the same volume of MUTATING errors does breach
        for _ in range(20):
            ss.record_error("suggest")
        clock.t = 100.0
        rows = {r["rule"]: r for r in eng.evaluate(force=True)}
        assert rows["SL603"]["status"] == "breach"

    def test_rule_table_shape(self):
        clock = _Clock()
        env = _engine(clock)
        rows = env["engine"].evaluate(force=True)
        assert {r["rule"] for r in rows} == {
            "SL601", "SL602", "SL603", "SL604", "SL605", "SL606",
            "SL607", "SL608",
        }
        for r in rows:
            assert r["status"] in ("ok", "breach", "no_data")
            assert "burn_fast" in r and "burn_slow" in r
            assert "objective" in r and "detail" in r
            assert r["window_fast_s"] >= 0 and r["window_slow_s"] >= 0

    def test_default_rules_rejects_unknown_override(self):
        with pytest.raises(ValueError):
            slo.default_rules(latency_ration={"ratio_max": 1})


# ---------------------------------------------------------------------
# flight recorder
# ---------------------------------------------------------------------


class TestFlightRecorder:
    def test_trace_ring_is_bounded(self):
        rec = slo.FlightRecorder(max_traces=4)
        for i in range(10):
            rec.record_trace({"trace_id": f"t{i}", "spans": []})
        assert rec.summary()["n_buffered_traces"] == 4
        records = rec._trace_records()
        assert [r["trace_id"] for r in records] == [
            "t6", "t7", "t8", "t9"
        ]

    def test_dump_roundtrip_and_validation(self, tmp_path):
        rec = slo.FlightRecorder(bundle_dir=str(tmp_path))
        rec.record_trace({"trace_id": "abc", "spans": []})
        rec.set_provider("dispatch", lambda: [{"sig": "s", "device_s": 1}])
        rec.set_provider("study_health", lambda: [{"study": "a"}])
        path = rec.dump("unit-test", context={"k": 1})
        assert path and os.path.exists(path)
        v = slo.validate_bundle(path)
        assert v["ok"] and v["n_torn"] == 0
        assert v["reason"] == "unit-test"
        assert v["kinds"]["trace"] == 1
        assert v["kinds"]["dispatch"] == 1
        assert v["kinds"]["study_health"] == 1
        assert v["trace_ids"] == ["abc"]
        records, _ = slo.read_bundle(path)
        assert records[0]["kind"] == "manifest"
        assert records[0]["context"] == {"k": 1}
        assert "version" in records[0]["build"]
        assert records[-1]["kind"] == "end"

    def test_torn_bundle_detected(self, tmp_path):
        rec = slo.FlightRecorder(bundle_dir=str(tmp_path))
        path = rec.dump("tear-me")
        with open(path, "r+b") as f:
            f.truncate(os.path.getsize(path) - 7)
        v = slo.validate_bundle(path)
        assert not v["ok"] and v["n_torn"] == 1

    def test_dump_without_dir_returns_none(self):
        rec = slo.FlightRecorder()
        assert rec.dump("nowhere") is None

    def test_bundles_pruned_to_bound(self, tmp_path):
        rec = slo.FlightRecorder(bundle_dir=str(tmp_path), max_bundles=3)
        for i in range(6):
            rec.dump(f"r{i}")
        names = sorted(
            n for n in os.listdir(tmp_path) if n.startswith("flightrec-")
        )
        assert len(names) == 3
        assert names[-1].endswith("r5.jsonl")

    def test_provider_failure_does_not_fail_the_dump(self, tmp_path):
        rec = slo.FlightRecorder(bundle_dir=str(tmp_path))

        def boom():
            raise RuntimeError("provider down")

        rec.set_provider("bad", boom)
        rec.set_provider("good", lambda: [{"x": 1}])
        path = rec.dump("resilient")
        v = slo.validate_bundle(path)
        assert v["ok"] and v["kinds"].get("good") == 1
        assert "bad" not in v["kinds"]

    def test_non_json_evidence_is_stringified(self, tmp_path):
        rec = slo.FlightRecorder(bundle_dir=str(tmp_path))
        rec.set_provider("odd", lambda: [{"obj": object()}])
        path = rec.dump("stringify")
        v = slo.validate_bundle(path)
        assert v["ok"] and v["kinds"]["odd"] == 1


class TestTracerRetention:
    def test_finish_feeds_recorder_even_when_head_dropped(self):
        rec = slo.FlightRecorder()
        # sample ~0 but slow-threshold set: traces are begun (buffered)
        # and head-DROPPED at finish — the recorder still sees them
        tracer = Tracer(sample=1e-9, slow_threshold_s=10.0)
        tracer.set_recorder(rec)
        tr = tracer.begin("some-id")
        with tracing.use_trace(tr):
            with tracing.span("root"):
                pass
        assert tracer.finish(tr) is False  # not written anywhere
        assert rec.summary()["n_buffered_traces"] == 1
        assert rec._trace_records()[0]["trace_id"] == tr.trace_id

    def test_disabled_tracer_feeds_nothing(self):
        rec = slo.FlightRecorder()
        tracer = Tracer(sample=0.0)
        tracer.set_recorder(rec)
        assert tracer.begin() is None
        assert tracer.finish(None) is False
        assert rec.summary()["n_buffered_traces"] == 0


class TestCrashHooks:
    def test_threading_excepthook_dumps_then_chains(self, tmp_path):
        rec = slo.FlightRecorder(bundle_dir=str(tmp_path))
        chained = []
        prev = threading.excepthook
        threading.excepthook = lambda args: chained.append(args)
        try:
            slo.install_crash_dump(rec)

            def boom():
                raise RuntimeError("unhandled")

            t = threading.Thread(target=boom)
            t.start()
            t.join()
        finally:
            threading.excepthook = prev
            import sys as _sys

            _sys.excepthook = _sys.__excepthook__
        assert rec.summary()["n_dumps"] == 1
        # the reason survives (filename-sanitized: ':' becomes '-')
        assert "crash-RuntimeError" in rec.summary()["last_bundle"]
        assert len(chained) == 1  # the previous hook still ran


# ---------------------------------------------------------------------
# service integration
# ---------------------------------------------------------------------


class TestServiceIntegration:
    def _service(self, tmp_path=None, **kwargs):
        from hyperopt_tpu.service import OptimizationService

        return OptimizationService(
            root=str(tmp_path / "root") if tmp_path is not None else None,
            batch_window=0.001, **kwargs,
        )

    def _drive(self, svc, sid="s0", n=4):
        svc.create_study(sid, SPACE, seed=3, algo_params=AP)
        for j in range(n):
            (t,) = svc.suggest(sid, idempotency_key=f"{sid}-k{j}")
            svc.report(
                sid, t["tid"], loss=float(j),
                idempotency_key=f"{sid}-r{j}",
            )

    def test_store_counters_reconcile_on_driven_service(self, tmp_path):
        svc = self._service(tmp_path)
        try:
            self._drive(svc, n=4)
            s = svc.store_stats.summary()
            # segmented default backend: one segment append per
            # trial-state transition (4 inserts + 4 result writes),
            # one record each on this unbatched path — and NO per-doc
            # writes at all
            assert s["doc_writes"] == 0
            assert s["segment_appends"] == 8
            assert s["segment_records"] == 8
            # one journal append per keyed mutation (4 suggests +
            # 4 reports; the create above was unkeyed)
            assert s["journal_appends"] == 8
            assert s["fsyncs"]["journal"] == 8
            # ZERO O(N) directory scans anywhere: the study-create
            # refresh replays the (empty) segment tail, the serve hot
            # path runs on the materialized view
            assert s["scans"] == 0
            assert s["refresh_local"] == 8
            assert s["refresh_full"] == 1
            # every fsync kind accounted: no doc fsyncs; one manifest
            # publish at create + one fsync per segment append
            assert s["fsyncs"].get("doc", 0) == 0
            assert s["fsyncs"]["segment"] == 9
            assert s["fsyncs"]["counter"] == 4
            # config + one seed cursor per suggest
            assert s["fsyncs"]["attachment"] == 5
        finally:
            _drain(svc)

    def test_warm_cold_split_attributes_first_touch(self, tmp_path):
        from hyperopt_tpu.algos import tpe_device

        svc = self._service(tmp_path)
        try:
            # force a fresh XLA trace: an earlier test in this process
            # may have compiled the same fused-program shapes already
            tpe_device.reset_device_state()
            self._drive(svc, n=4)
            s = svc.stats.summary()
            warm, cold = (
                s["suggest_latency_warm"], s["suggest_latency_cold"]
            )
            assert warm["count"] + cold["count"] == 4
            # the first fused dispatch compiles: at least one cold
            assert cold["count"] >= 1
            hist_total = s["suggest_latency"]
            assert hist_total["p99_ms"] is not None
        finally:
            _drain(svc)

    def test_alerts_payload_and_metrics_families(self, tmp_path):
        svc = self._service(tmp_path)
        try:
            self._drive(svc, n=2)
            al = svc.alerts()
            assert {r["rule"] for r in al["rules"]} == {
                "SL601", "SL602", "SL603", "SL604", "SL605", "SL606",
                "SL607", "SL608",
            }
            assert al["breaching"] == [
                r["rule"] for r in al["rules"] if not r["ok"]
            ]
            assert al["recorder"] is not None
            text = svc.metrics_text()
            for family in (
                "hyperopt_slo_status", "hyperopt_slo_burn_rate",
                "hyperopt_slo_breaches_total", "hyperopt_build_info",
                "hyperopt_store_fsyncs_total",
                "hyperopt_store_fsync_duration_seconds_bucket",
                "hyperopt_store_scans_total",
                "hyperopt_service_suggest_split_latency_ms",
                "hyperopt_service_errors_total",
            ):
                assert family in text, family
        finally:
            _drain(svc)

    def test_alerts_over_http_and_client(self, tmp_path):
        from hyperopt_tpu.service import ServiceClient, ServiceServer

        svc = self._service(tmp_path)
        server = ServiceServer(svc).start()
        try:
            client = ServiceClient(server.url)
            al = client.alerts()
            assert len(al["rules"]) == 8
            st = client.service_status()
            assert "version" in st and "started_at" in st
            assert st["version"]["version"]
            assert "store" in st and "slo_breaching" in st
        finally:
            server.stop()

    def test_slo_disabled_is_fully_off(self, tmp_path):
        from hyperopt_tpu.parallel.file_trials import store_stats

        svc = self._service(tmp_path, slo_enabled=False)
        try:
            assert store_stats() is not svc.store_stats
            # no breach-triggered dumps either: the engine carries no
            # recorder, so a /v1/alerts poll stays passive
            assert svc.slo.recorder is None
            self._drive(svc, n=2)
            assert svc.store_stats.summary()["doc_writes"] == 0
            assert "hyperopt_slo_status" not in svc.metrics_text()
        finally:
            _drain(svc)

    def test_close_uninstalls_store_stats(self, tmp_path):
        from hyperopt_tpu.parallel.file_trials import store_stats

        svc = self._service(tmp_path)
        assert store_stats() is svc.store_stats
        _drain(svc)
        assert store_stats() is None

    def test_status_json_serializable(self, tmp_path):
        svc = self._service(tmp_path)
        try:
            self._drive(svc, n=2)
            json.dumps(svc.service_status())
            json.dumps(svc.alerts())
        finally:
            _drain(svc)


# ---------------------------------------------------------------------
# race lint registration (satellite convention)
# ---------------------------------------------------------------------


def test_slo_registered_and_race_clean():
    from hyperopt_tpu.analysis import discover_race_files, lint_races

    slo_paths = [p for p in discover_race_files() if p.endswith("slo.py")]
    assert slo_paths, "slo.py must be race-linted"
    diags = lint_races(paths=slo_paths)
    assert not diags, [str(d) for d in diags]
    src = open(slo_paths[0]).read()
    assert "# guarded-by: _lock" in src
