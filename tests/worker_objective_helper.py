"""Module-level objective for the subprocess-worker E2E test.

Lives in its own importable module (not the test file) because the
driver pickles the Domain by reference into the queue's attachment blob
(reference semantics: the mongo 'domain_attachment' GridFS blob) and the
worker *process* must re-import it.
"""


def quad_objective(cfg):
    return (cfg["x"] - 3.0) ** 2


def slow_quad_objective(cfg):
    """~2s objective for the graceful-shutdown test: long enough to land
    a SIGTERM while the trial is in flight, short enough for CI."""
    import time

    time.sleep(2.0)
    return (cfg["x"] - 3.0) ** 2
