"""Module-level objective for the subprocess-worker E2E test.

Lives in its own importable module (not the test file) because the
driver pickles the Domain by reference into the queue's attachment blob
(reference semantics: the mongo 'domain_attachment' GridFS blob) and the
worker *process* must re-import it.
"""


def quad_objective(cfg):
    return (cfg["x"] - 3.0) ** 2
