"""End-to-end driver tests (reference parity: hyperopt/tests/test_fmin.py):
warm start, early stop, timeout, loss_threshold, save/resume, exceptions,
space_eval, determinism.
"""

import os
import pickle
import time

import numpy as np
import pytest

from hyperopt_tpu import (
    STATUS_FAIL,
    STATUS_OK,
    Trials,
    fmin,
    hp,
    no_progress_loss,
    space_eval,
    trials_from_docs,
)
from hyperopt_tpu.algos import rand
from hyperopt_tpu.base import JOB_STATE_ERROR
from hyperopt_tpu.exceptions import AllTrialsFailed
from hyperopt_tpu.models import domains


def quad(c):
    return (c["x"] - 3) ** 2


QSPACE = {"x": hp.uniform("x", -5, 5)}


def test_fmin_rand_quadratic():
    trials = Trials()
    best = fmin(
        quad,
        QSPACE,
        algo=rand.suggest,
        max_evals=100,
        trials=trials,
        rstate=np.random.default_rng(0),
        show_progressbar=False,
        verbose=False,
    )
    assert len(trials) == 100
    assert abs(best["x"] - 3) < 0.5
    assert min(trials.losses()) < 0.2


def test_fmin_determinism():
    def run():
        return fmin(
            quad,
            QSPACE,
            algo=rand.suggest,
            max_evals=20,
            rstate=np.random.default_rng(123),
            show_progressbar=False,
            verbose=False,
        )

    assert run() == run()


def test_points_to_evaluate():
    trials = Trials()
    fmin(
        quad,
        QSPACE,
        algo=rand.suggest,
        max_evals=5,
        trials=trials,
        points_to_evaluate=[{"x": 3.0}, {"x": -4.0}],
        rstate=np.random.default_rng(0),
        show_progressbar=False,
        verbose=False,
    )
    assert trials.trials[0]["misc"]["vals"]["x"] == [3.0]
    assert trials.trials[1]["misc"]["vals"]["x"] == [-4.0]
    assert trials.results[0]["loss"] == 0.0
    assert len(trials) == 5


def test_points_to_evaluate_without_trials():
    best = fmin(
        quad,
        QSPACE,
        algo=rand.suggest,
        max_evals=3,
        points_to_evaluate=[{"x": 3.0}],
        rstate=np.random.default_rng(0),
        show_progressbar=False,
        verbose=False,
    )
    assert best["x"] == 3.0


def test_early_stop_no_progress_loss():
    trials = Trials()
    fmin(
        lambda c: 10.0,  # never improves
        QSPACE,
        algo=rand.suggest,
        max_evals=500,
        trials=trials,
        early_stop_fn=no_progress_loss(10),
        rstate=np.random.default_rng(0),
        show_progressbar=False,
        verbose=False,
    )
    assert len(trials) < 30


def test_loss_threshold_stops():
    trials = Trials()
    fmin(
        quad,
        QSPACE,
        algo=rand.suggest,
        max_evals=10000,
        trials=trials,
        loss_threshold=5.0,
        rstate=np.random.default_rng(0),
        show_progressbar=False,
        verbose=False,
    )
    assert len(trials) < 10000
    assert min(trials.losses()) <= 5.0


def test_timeout_stops():
    trials = Trials()
    t0 = time.time()
    fmin(
        lambda c: time.sleep(0.02) or quad(c),
        QSPACE,
        algo=rand.suggest,
        max_evals=100000,
        trials=trials,
        timeout=0.5,
        rstate=np.random.default_rng(0),
        show_progressbar=False,
        verbose=False,
    )
    assert time.time() - t0 < 5.0
    assert 0 < len(trials) < 100000


def test_trials_save_file_resume(tmp_path):
    save = str(tmp_path / "trials.pkl")
    fmin(
        quad, QSPACE, algo=rand.suggest, max_evals=10,
        rstate=np.random.default_rng(0), trials_save_file=save,
        show_progressbar=False, verbose=False,
    )
    with open(save, "rb") as f:
        t1 = pickle.load(f)
    assert len(t1) == 10
    # resume: continues from the saved file up to 25 total
    fmin(
        quad, QSPACE, algo=rand.suggest, max_evals=25,
        rstate=np.random.default_rng(1), trials_save_file=save,
        show_progressbar=False, verbose=False,
    )
    with open(save, "rb") as f:
        t2 = pickle.load(f)
    assert len(t2) == 25
    # first 10 trials identical to the first run
    assert [t["tid"] for t in t2.trials[:10]] == [t["tid"] for t in t1.trials]


def test_catch_eval_exceptions():
    calls = []

    def sometimes_fails(c):
        calls.append(1)
        if len(calls) % 3 == 0:
            raise RuntimeError("boom")
        return quad(c)

    trials = Trials()
    fmin(
        sometimes_fails,
        QSPACE,
        algo=rand.suggest,
        max_evals=10,
        trials=trials,
        catch_eval_exceptions=True,
        rstate=np.random.default_rng(0),
        show_progressbar=False,
        verbose=False,
    )
    errors = [t for t in trials._dynamic_trials if t["state"] == JOB_STATE_ERROR]
    assert len(errors) >= 1
    assert all("boom" in t["misc"]["error"][1] for t in errors)
    # error trials are filtered from the refreshed view
    assert all(t["state"] != JOB_STATE_ERROR for t in trials.trials)


def test_uncaught_exception_propagates():
    def always_fails(c):
        raise RuntimeError("kaput")

    with pytest.raises(RuntimeError, match="kaput"):
        fmin(
            always_fails, QSPACE, algo=rand.suggest, max_evals=3,
            rstate=np.random.default_rng(0),
            show_progressbar=False, verbose=False,
        )


def test_status_fail_trials_excluded_from_argmin():
    def fn(c):
        if c["x"] < 0:
            return {"status": STATUS_FAIL}
        return {"status": STATUS_OK, "loss": quad(c)}

    trials = Trials()
    best = fmin(
        fn, QSPACE, algo=rand.suggest, max_evals=50, trials=trials,
        rstate=np.random.default_rng(0), show_progressbar=False, verbose=False,
    )
    assert best["x"] >= 0


def test_all_trials_failed_raises():
    def fn(c):
        return {"status": STATUS_FAIL}

    with pytest.raises(AllTrialsFailed):
        fmin(
            fn, QSPACE, algo=rand.suggest, max_evals=5,
            rstate=np.random.default_rng(0),
            show_progressbar=False, verbose=False,
        )


def test_return_argmin_false():
    rval = fmin(
        quad, QSPACE, algo=rand.suggest, max_evals=3,
        rstate=np.random.default_rng(0), return_argmin=False,
        show_progressbar=False, verbose=False,
    )
    assert rval is None


def test_trials_fmin_method():
    trials = Trials()
    best = trials.fmin(
        quad, QSPACE, algo=rand.suggest, max_evals=10,
        rstate=np.random.default_rng(0), show_progressbar=False, verbose=False,
    )
    assert len(trials) == 10
    assert "x" in best


def test_space_eval_roundtrip():
    space = hp.choice(
        "m",
        [
            {"kind": "a", "p": hp.uniform("p", 0, 1)},
            {"kind": "b", "q": hp.loguniform("q", -2, 2)},
        ],
    )
    assert space_eval(space, {"m": 0, "p": 0.5}) == {"kind": "a", "p": 0.5}
    out = space_eval(space, {"m": 1, "q": 1.5})
    assert out["kind"] == "b" and out["q"] == 1.5


def test_fmin_conditional_space_end_to_end():
    d = domains.get("q1_choice")
    trials = Trials()
    best = fmin(
        d.fn, d.space, algo=rand.suggest, max_evals=d.quality_evals,
        trials=trials, rstate=np.random.default_rng(0),
        show_progressbar=False, verbose=False,
    )
    assert min(trials.losses()) < d.quality_threshold
    # each trial has exactly one of xl/xr active
    for m in trials.miscs:
        assert (len(m["idxs"]["xl"]) == 1) != (len(m["idxs"]["xr"]) == 1)


def test_fmin_progressbar_smoke(capsys):
    fmin(
        quad, QSPACE, algo=rand.suggest, max_evals=5,
        rstate=np.random.default_rng(0), verbose=False,
    )  # default show_progressbar=True exercises tqdm path


def test_max_queue_len_batching():
    seen_batches = []

    def counting_suggest(new_ids, domain, trials, seed):
        seen_batches.append(len(new_ids))
        return rand.suggest(new_ids, domain, trials, seed)

    fmin(
        quad, QSPACE, algo=counting_suggest, max_evals=12, max_queue_len=4,
        rstate=np.random.default_rng(0), show_progressbar=False, verbose=False,
    )
    assert max(seen_batches) == 4
