"""Real-TPU smoke tier (`pytest -m tpu`, see conftest.py).

Everything here runs with ``interpret=False`` so Mosaic's lowering checks
actually execute — the exact class of failure (BlockSpec tiling, matmul
precision passes) that the CPU-mesh suite structurally cannot catch
(round 3's bench crash: kernels only ever tested under the interpreter).

Kept tiny: through the remote-TPU tunnel every compile is a network
round trip, so this tier is a handful of small programs, not a suite.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

pytestmark = pytest.mark.tpu


def _mk_mixture(rng, k):
    w = rng.random(k).astype(np.float32)
    w /= w.sum()
    mu = rng.normal(size=k).astype(np.float32)
    sigma = (0.1 + rng.random(k)).astype(np.float32)
    return jnp.asarray(w), jnp.asarray(mu), jnp.asarray(sigma)


def _truth_pair_score(z, params, kb):
    z = np.asarray(z, np.float64)
    P = np.asarray(params, np.float64)
    f = np.stack([z * z, z, np.ones_like(z)], 1)
    comp = f @ P

    def lse(c):
        m = c.max(1)
        return m + np.log(np.exp(c - m[:, None]).sum(1))

    return lse(comp[:, :kb]) - lse(comp[:, kb:])


def test_pallas_scorer_lowers_and_matches_f64():
    from hyperopt_tpu.ops.pallas_gmm import pair_score_pallas
    from hyperopt_tpu.ops.score import pair_params

    rng = np.random.default_rng(0)
    kb, ka, C = 25, 999, 513  # deliberately unaligned
    params = pair_params(*_mk_mixture(rng, kb), *_mk_mixture(rng, ka))
    z = jnp.asarray(rng.normal(size=C).astype(np.float32))
    got = np.asarray(pair_score_pallas(z, params, kb))
    ref = _truth_pair_score(z, params, kb)
    np.testing.assert_allclose(got, ref, atol=1e-3)


def test_pallas_batched_scorer_lowers_and_matches_f64():
    from hyperopt_tpu.ops.pallas_gmm import pair_score_pallas_batched
    from hyperopt_tpu.ops.score import pair_params

    rng = np.random.default_rng(1)
    L, kb, ka, C = 3, 25, 300, 640
    params = jnp.stack(
        [pair_params(*_mk_mixture(rng, kb), *_mk_mixture(rng, ka)) for _ in range(L)]
    )
    z = jnp.asarray(rng.normal(size=(L, C)).astype(np.float32))
    got = np.asarray(pair_score_pallas_batched(z, params, kb))
    ref = np.stack([_truth_pair_score(z[l], params[l], kb) for l in range(L)])
    np.testing.assert_allclose(got, ref, atol=1e-3)


def test_xla_scorer_precision_at_scale():
    # guards the Precision.HIGHEST matmul: default bf16 passes drift by
    # ~1e0 absolute at 10k components, which randomizes the EI argmax
    from hyperopt_tpu.ops.score import pair_params, pair_score

    rng = np.random.default_rng(2)
    kb, ka, C = 200, 9800, 4096
    params = pair_params(*_mk_mixture(rng, kb), *_mk_mixture(rng, ka))
    z = jnp.asarray(rng.normal(size=C).astype(np.float32))
    got = np.asarray(pair_score(z, params, kb))
    ref = _truth_pair_score(z, params, kb)
    np.testing.assert_allclose(got, ref, atol=2e-3)


def test_gmm_sample_on_device():
    from hyperopt_tpu.ops import gmm as gmm_ops

    rng = np.random.default_rng(3)
    w, mu, sigma = _mk_mixture(rng, 16)
    key = jax.random.PRNGKey(0)
    s = np.asarray(
        gmm_ops.gmm_sample(
            key, w, mu, sigma, np.float32(-10.0), np.float32(10.0),
            np.float32(0.0), 512, False,
        )
    )
    assert s.shape == (512,)
    assert np.all((s >= -10.0) & (s <= 10.0))
    assert np.std(s) > 0.1


def test_scorer_probe_selects_pallas_on_tpu(monkeypatch):
    from hyperopt_tpu.algos import tpe

    monkeypatch.delenv("HYPEROPT_TPU_SCORER", raising=False)
    monkeypatch.setattr(tpe, "_probed_scorer", None)
    assert tpe._use_pallas() == "pallas"  # probe must succeed on real TPU


def test_tpe_fmin_end_to_end_on_tpu():
    # full driver loop: DeviceHistory sync + multi_family_suggest on hardware
    from hyperopt_tpu import Trials, fmin, hp, tpe

    space = {
        "x": hp.uniform("x", -5.0, 5.0),
        "y": hp.loguniform("y", np.log(1e-3), np.log(1e1)),
        "c": hp.choice("c", [0.0, 1.0]),
    }

    def loss(d):
        return (d["x"] - 1.0) ** 2 + (np.log(d["y"]) + 2.0) ** 2 + d["c"]

    trials = Trials()
    best = fmin(
        loss,
        space,
        algo=tpe.suggest,
        max_evals=30,
        trials=trials,
        rstate=np.random.default_rng(0),
        show_progressbar=False,
    )
    assert len(trials.trials) == 30
    assert trials.best_trial["result"]["loss"] < 10.0
    assert -5.0 <= best["x"] <= 5.0


def test_pallas_fma_variant_lowers_and_matches_f64():
    # the VPU-FMA quadratic path must lower under Mosaic and agree with
    # the f64 truth as tightly as the MXU dot path
    from hyperopt_tpu.ops.pallas_gmm import pair_score_pallas
    from hyperopt_tpu.ops.score import pair_params

    rng = np.random.default_rng(2)
    kb, ka, C = 25, 999, 513
    params = pair_params(*_mk_mixture(rng, kb), *_mk_mixture(rng, ka))
    z = jnp.asarray(rng.normal(size=C).astype(np.float32))
    got = np.asarray(pair_score_pallas(z, params, kb, fma=True))
    ref = _truth_pair_score(z, params, kb)
    np.testing.assert_allclose(got, ref, atol=1e-3)


def test_fma_timing_probe_selects_a_mode():
    # the once-per-process kernel-mode probe must run on hardware and
    # leave a measured default behind; restore the prior value so the
    # rest of the tier keeps its original (order-independent) default
    from hyperopt_tpu.algos import tpe
    from hyperopt_tpu.ops import pallas_gmm

    prior = pallas_gmm._fma_measured_default
    prior_ub = pallas_gmm._fma_measured_default_unbatched
    try:
        tpe._fma_timing_probe(k_total=8192 + 32, n_cand=2048, iters=4)
        assert pallas_gmm._fma_measured_default in (True, False)
        assert pallas_gmm._fma_measured_default_unbatched in (True, False)
    finally:
        pallas_gmm._fma_measured_default = prior
        pallas_gmm._fma_measured_default_unbatched = prior_ub


def test_mesh_suggest_unified_path_on_chip():
    """tpe.suggest(mesh=…) on a 1-chip mesh: the unified device-history
    route (shard_map pair scorer included) must lower and run on real
    hardware, not only on the virtual CPU mesh."""
    from hyperopt_tpu import Trials, hp
    from hyperopt_tpu.base import JOB_STATE_DONE, STATUS_OK, Domain
    from hyperopt_tpu.algos import tpe
    from hyperopt_tpu.parallel.sharding import default_mesh

    rng = np.random.default_rng(2)
    space = {"x": hp.uniform("x", -5, 5), "w": hp.quniform("w", 0, 100, 5)}
    domain = Domain(lambda c: c["x"] ** 2, space)
    docs = []
    for i in range(40):
        x = float(rng.uniform(-5, 5))
        w = float(np.round(rng.uniform(0, 100) / 5) * 5)
        docs.append({
            "tid": i, "spec": None,
            "result": {"status": STATUS_OK, "loss": x * x},
            "misc": {"tid": i, "cmd": None,
                     "idxs": {"x": [i], "w": [i]},
                     "vals": {"x": [x], "w": [w]}},
            "state": JOB_STATE_DONE, "owner": None,
            "book_time": None, "refresh_time": None, "exp_key": None,
        })
    trials = Trials()
    trials._insert_trial_docs(docs)
    trials.refresh()
    mesh = default_mesh()  # 1 real chip -> dp=1, sp=1 (shard_map still runs)
    out = tpe.suggest([100], domain, trials, seed=7, mesh=mesh,
                      n_EI_candidates=512)
    v = out[0]["misc"]["vals"]
    assert -5.0 <= v["x"][0] <= 5.0
    assert v["w"][0] % 5 == 0
