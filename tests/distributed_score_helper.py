"""Subprocess body for the 2-process jax.distributed test.

Each process owns 2 virtual CPU devices; together they form the (2, 2)
dp×sp global mesh. The PRODUCTION sharded scorer
(`parallel.sharding.make_sharded_score`) then runs as one SPMD program:
candidates split over dp (one process's devices never see the other's
candidates), mixture components split over sp, and the blockwise
logsumexp's pmax/psum collectives cross the process boundary over the
Gloo transport — the CPU stand-in for DCN.

Usage: python distributed_score_helper.py <process_id> <coordinator_port>
Prints DIST_SCORE_OK on success; any assert kills the exit code.
"""

import os
import sys

pid = int(sys.argv[1])
port = sys.argv[2]

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np

from hyperopt_tpu.parallel import distributed
from hyperopt_tpu.parallel.sharding import make_sharded_score

assert distributed.initialize(
    coordinator_address=f"localhost:{port}", num_processes=2, process_id=pid
)
assert jax.process_count() == 2
assert len(jax.devices()) == 4
assert distributed.is_coordinator() == (pid == 0)

# the production orientation (global_mesh, process-major) deliberately
# keeps sp INTRA-process — the component-axis psum/pmax are the heavy
# collectives and belong on the fast interconnect (ICI), while dp needs
# no communication at all. That layout would let this test pass without
# any cross-process traffic, so here the device grid is TRANSPOSED to
# force every sp collective across the process (Gloo/DCN) boundary.
import numpy as _np
from jax.sharding import Mesh

_grid = _np.array(jax.devices()).reshape(2, 2).T
assert {d.process_index for d in _grid[0]} == {0, 1}, "sp must span processes"
mesh = Mesh(_grid, ("dp", "sp"))

# the production helper still builds (and is pinned by) the ICI-friendly
# orientation
_prod = distributed.global_mesh(shape=(2, 2))
assert _prod.devices.shape == (2, 2)

from jax.sharding import NamedSharding, PartitionSpec as P

rng = np.random.default_rng(0)  # same seed in both processes: SPMD inputs
C, K = 8, 16
cand = rng.uniform(-2, 2, C).astype(np.float32)
mk = lambda: (
    (lambda a: (a / a.sum()).astype(np.float32))(np.abs(rng.normal(size=K)) + 0.1),
    rng.normal(size=K).astype(np.float32),
    (np.abs(rng.normal(size=K)) + 0.2).astype(np.float32),
)
below, above = mk(), mk()
low, high = np.float32(-4.0), np.float32(4.0)


def garr(x, spec):
    x = np.asarray(x)
    return jax.make_array_from_callback(
        x.shape, NamedSharding(mesh, spec), lambda idx: x[idx]
    )


scorer = make_sharded_score(mesh)
out = scorer(
    garr(cand, P("dp")),
    *[garr(a, P("sp")) for a in below],
    *[garr(a, P("sp")) for a in above],
    garr(low, P()),
    garr(high, P()),
)

# exact reference from the single-device density (both processes compute
# the full answer from the shared numpy inputs)
from hyperopt_tpu.ops.gmm import gmm_lpdf

ref = np.asarray(
    gmm_lpdf(cand, *below, low, high, 0.0, False, False)
) - np.asarray(gmm_lpdf(cand, *above, low, high, 0.0, False, False))

# each process checks the shards it can address (its own dp rows)
for shard in out.addressable_shards:
    idx = shard.index[0]
    np.testing.assert_allclose(np.asarray(shard.data), ref[idx], atol=1e-4)

print(f"DIST_SCORE_OK pid={pid}", flush=True)
