"""End-to-end request tracing (ISSUE 6).

Covers the tentpole contracts:

- spans nest within a thread and NEVER leak across threads (the
  scheduler-worker vs handler split);
- batched requests share the device span with pro-rata attribution that
  sums back to the batch total;
- a forced recompile attaches a compile span to exactly ONE trace (the
  batch leader that paid for it), keyed by (trial-bucket, family);
- the trace log survives a mid-write SIGKILL (CRC + leading-newline
  resync, like the response journal);
- sampling 0 makes tracing a no-op on the hot path (null-span
  singleton, no Trace allocation, no log);
- idempotent replays are tagged (``replay=true``) and excluded from
  latency accounting;
- chaos injections are stamped with the active trace id;
- ``scripts/trace_report.py`` aggregates coverage/phases/compiles.
"""

import json
import os
import signal
import subprocess
import sys
import threading
import time

import pytest

from hyperopt_tpu import hp, tracing
from hyperopt_tpu.observability import LatencyHistogram, ServiceStats
from hyperopt_tpu.tracing import (
    NULL_SPAN,
    Trace,
    Tracer,
    format_record,
    head_sampled,
    parse_trace_log,
    read_trace_log,
)

sys.path.insert(
    0,
    os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "scripts",
    ),
)

SPACE = {
    "x": hp.uniform("x", -5, 5),
    "c": hp.choice("c", ["a", "b"]),
}
AP = {"n_startup_jobs": 1, "n_EI_candidates": 8}


def _drain(svc):
    try:
        svc.close(timeout=10.0)
    except Exception:
        pass


# ---------------------------------------------------------------------
# span model
# ---------------------------------------------------------------------


class TestSpans:
    def test_nesting_within_a_thread(self):
        tracer = Tracer(sample=1.0)
        tr = tracer.begin()
        with tracing.use_trace(tr):
            with tracing.span("outer") as outer:
                with tracing.span("inner", k=1) as inner:
                    assert tracing.current_span() is inner
                assert tracing.current_span() is outer
            assert tracing.current_span() is None
        spans = {s.name: s for s in tr.spans()}
        assert spans["inner"].parent_id == spans["outer"].span_id
        assert spans["outer"].parent_id is None
        assert tr.root is spans["outer"]
        assert spans["inner"].attrs == {"k": 1}
        assert spans["inner"].duration_s >= 0.0

    def test_never_leaks_across_threads(self):
        tracer = Tracer(sample=1.0)
        tr = tracer.begin()
        seen = {}

        def probe():
            seen["trace"] = tracing.current_trace()
            seen["span"] = tracing.span("should_be_null")

        with tracing.use_trace(tr):
            with tracing.span("root"):
                t = threading.Thread(target=probe)
                t.start()
                t.join()
        # a freshly spawned thread starts UNBOUND even while the parent
        # holds an open span — no implicit inheritance
        assert seen["trace"] is None
        assert seen["span"] is NULL_SPAN
        assert [s.name for s in tr.spans()] == ["root"]

    def test_use_trace_restores_previous_binding(self):
        tracer = Tracer(sample=1.0)
        tr1, tr2 = tracer.begin(), tracer.begin()
        with tracing.use_trace(tr1):
            assert tracing.current_trace() is tr1
            with tracing.use_trace(tr2):
                assert tracing.current_trace() is tr2
            assert tracing.current_trace() is tr1
        assert tracing.current_trace() is None

    def test_explicit_parent_for_cross_thread_handoff(self):
        tracer = Tracer(sample=1.0)
        tr = tracer.begin()
        with tracing.use_trace(tr):
            with tracing.span("root") as root:
                pass

        def worker():
            with tracing.use_trace(tr, parent=root):
                with tracing.span("child"):
                    pass

        t = threading.Thread(target=worker)
        t.start()
        t.join()
        spans = {s.name: s for s in tr.spans()}
        assert spans["child"].parent_id == spans["root"].span_id

    def test_span_error_attr_on_exception(self):
        tracer = Tracer(sample=1.0)
        tr = tracer.begin()
        with pytest.raises(RuntimeError):
            with tracing.use_trace(tr):
                with tracing.span("boom"):
                    raise RuntimeError("x")
        (sp,) = tr.spans()
        assert sp.attrs["error"] == "RuntimeError"
        assert sp.t1 is not None


# ---------------------------------------------------------------------
# sampling / disabled hot path
# ---------------------------------------------------------------------


class TestSampling:
    def test_sample_zero_is_disabled(self):
        tracer = Tracer(sample=0.0)
        assert not tracer.enabled
        assert tracer.begin() is None
        # the null path: one shared singleton, nothing allocated
        with tracing.use_trace(None):
            assert tracing.span("anything") is NULL_SPAN
            assert tracing.add_event("anything") is NULL_SPAN
            assert tracing.current_trace_id() is None
        assert tracer.summary()["n_begun"] == 0

    def test_sample_zero_noop_survives_recorder_retention(self, monkeypatch):
        """ISSUE-9 guard: the flight recorder's trace-ring retention
        hooks Tracer.finish, and must NOT regress the sample-0 fast
        path — with no trace bound, span() still returns the shared
        singleton without allocating, locking, or reading a clock."""
        from hyperopt_tpu.slo import FlightRecorder

        recorder = FlightRecorder()
        tracer = Tracer(sample=0.0)
        tracer.set_recorder(recorder)
        # off still means off: no traces begin, the ring stays empty
        assert not tracer.enabled
        assert tracer.begin() is None
        assert tracer.finish(None) is False
        assert recorder.summary()["n_buffered_traces"] == 0
        # no clock read on the unbound span path: a poisoned monotonic
        # clock would raise if span()/add_event() ever touched it
        def poisoned():
            raise AssertionError("unbound span path read the clock")

        monkeypatch.setattr(tracing.time, "monotonic", poisoned)
        with tracing.use_trace(None):
            assert tracing.span("anything", k=1) is NULL_SPAN
            assert tracing.add_event("anything") is NULL_SPAN
        # no per-call allocation: the singleton is returned, not built
        import tracemalloc

        tracemalloc.start()
        try:
            tracing.span("hot")  # warm any lazy interning
            before = tracemalloc.take_snapshot()
            for _ in range(100):
                tracing.span("hot")
            after = tracemalloc.take_snapshot()
        finally:
            tracemalloc.stop()
        grown = [
            s for s in after.compare_to(before, "lineno")
            if s.size_diff > 0 and "tracing.py" in str(s.traceback)
        ]
        assert not grown, grown

    def test_slow_threshold_alone_enables(self):
        tracer = Tracer(sample=0.0, slow_threshold_s=0.5)
        assert tracer.enabled
        assert tracer.begin() is not None

    def test_head_dropped_requests_buffer_nothing(self):
        # without a slow threshold a head-dropped request must not pay
        # for Trace allocation and span bookkeeping at all
        tracer = Tracer(sample=1e-9)
        drop_id = "some-id"
        assert not head_sampled(drop_id, tracer.sample)
        assert tracer.begin(drop_id) is None
        s = tracer.summary()
        assert s["n_dropped"] == 1 and s["n_begun"] == 0
        # WITH a slow threshold the same request buffers (tail rescue
        # needs the spans to know the duration)
        rescue = Tracer(sample=1e-9, slow_threshold_s=0.5)
        assert rescue.begin(drop_id) is not None

    def test_counters_reconcile_without_log_path(self):
        tracer = Tracer(sample=1.0)  # kept traces, nowhere to land
        tr = tracer.begin()
        with tracing.use_trace(tr):
            with tracing.span("root"):
                pass
        assert tracer.finish(tr) is False
        s = tracer.summary()
        assert s["n_unlogged"] == 1
        assert s["n_begun"] == (
            s["n_written"] + s["n_dropped"] + s["n_unlogged"]
        )

    def test_cli_refuses_tracing_without_a_log_destination(self):
        from hyperopt_tpu.service.__main__ import main

        assert main(["--trace-sample", "1.0", "--port", "0"]) == 2

    def test_head_sampling_is_deterministic_in_the_id(self):
        decisions = {head_sampled("trace-abc", 0.5) for _ in range(32)}
        assert len(decisions) == 1
        assert head_sampled("x", 1.0) and not head_sampled("x", 0.0)
        # roughly the configured fraction samples
        n = sum(head_sampled(f"t{i}", 0.25) for i in range(2000))
        assert 0.15 < n / 2000 < 0.35

    def test_slow_trace_written_despite_head_drop(self, tmp_path):
        log = str(tmp_path / "t.jsonl")
        tracer = Tracer(path=log, sample=1e-9, slow_threshold_s=0.01)
        # fast trace: head-dropped
        tr = tracer.begin()
        with tracing.use_trace(tr):
            with tracing.span("root"):
                pass
        assert tracer.finish(tr) is False
        # slow trace: rescued by the threshold
        tr = tracer.begin()
        with tracing.use_trace(tr):
            with tracing.span("root"):
                time.sleep(0.02)
        assert tracer.finish(tr) is True
        records, torn = read_trace_log(log)
        assert torn == 0 and len(records) == 1
        assert records[0]["duration_s"] >= 0.01


# ---------------------------------------------------------------------
# crash-tolerant log
# ---------------------------------------------------------------------


class TestTraceLog:
    def _write_n(self, tracer, n):
        for i in range(n):
            tr = tracer.begin()
            with tracing.use_trace(tr):
                with tracing.span("root", i=i):
                    pass
            tracer.finish(tr)

    def test_roundtrip_and_resync_after_torn_tail(self, tmp_path):
        log = str(tmp_path / "t.jsonl")
        tracer = Tracer(path=log, sample=1.0)
        self._write_n(tracer, 5)
        # tear the tail mid-record (what a SIGKILL mid-append leaves)
        with open(log, "r+b") as f:
            f.truncate(os.path.getsize(log) - 9)
        records, torn = read_trace_log(log)
        assert torn == 1 and len(records) == 4
        # the NEXT append's leading newline re-synchronizes the reader
        self._write_n(tracer, 1)
        records, torn = read_trace_log(log)
        assert torn == 1 and len(records) == 5
        assert all(r["root"] == "root" for r in records)

    def test_survives_midwrite_sigkill(self, tmp_path):
        """A writer SIGKILL'd at a random moment leaves at most one torn
        record, and the log stays appendable + readable."""
        log = str(tmp_path / "t.jsonl")
        child = subprocess.Popen(
            [sys.executable, "-c", f"""
import sys; sys.path.insert(0, {os.path.dirname(os.path.dirname(os.path.abspath(__file__)))!r})
from hyperopt_tpu import tracing
tracer = tracing.Tracer(path={log!r}, sample=1.0)
i = 0
while True:
    tr = tracer.begin()
    with tracing.use_trace(tr):
        with tracing.span("root", i=i):
            pass
    tracer.finish(tr)
    i += 1
"""],
        )
        try:
            deadline = time.monotonic() + 20.0
            while time.monotonic() < deadline:
                if os.path.exists(log) and os.path.getsize(log) > 2000:
                    break
                time.sleep(0.01)
        finally:
            child.send_signal(signal.SIGKILL)
            child.wait(timeout=10)
        assert os.path.getsize(log) > 0
        records, torn = read_trace_log(log)
        assert torn <= 1
        assert len(records) >= 1
        # still appendable after the crash
        tracer = Tracer(path=log, sample=1.0)
        self._write_n(tracer, 1)
        records2, torn2 = read_trace_log(log)
        assert len(records2) == len(records) + 1 and torn2 == torn

    def test_rotation_bounds_the_log(self, tmp_path):
        log = str(tmp_path / "t.jsonl")
        tracer = Tracer(path=log, sample=1.0, max_bytes=2000)
        self._write_n(tracer, 60)
        assert os.path.getsize(log) <= 2000
        assert os.path.exists(log + ".1")
        assert tracer.summary()["n_rotations"] >= 1
        # both generations parse; total stays bounded
        records, torn = read_trace_log(log)
        assert torn == 0 and 0 < len(records) < 60

    def test_format_crc_rejects_corruption(self):
        rec = format_record({"a": 1})
        records, torn = parse_trace_log(rec)
        assert records == [{"a": 1}] and torn == 0
        records, torn = parse_trace_log(rec[:-2] + b"xx")
        assert records == [] and torn == 1


# ---------------------------------------------------------------------
# histogram (satellite: exported quantiles from buckets, not the ring)
# ---------------------------------------------------------------------


class TestLatencyHistogram:
    def test_quantiles_interpolate_within_buckets(self):
        h = LatencyHistogram(buckets=(0.01, 0.1, 1.0))
        assert h.quantile(0.5) is None
        for v in (0.005, 0.005, 0.05, 0.5):
            h.observe(v)
        # p50 (rank 2) sits at the edge of the first bucket
        assert h.quantile(0.5) == pytest.approx(0.01, abs=1e-9)
        # p75 (rank 3) is inside (0.01, 0.1]
        assert 0.01 < h.quantile(0.75) <= 0.1
        assert h.total == 4 and h.sum_s == pytest.approx(0.56)

    def test_overflow_bucket_reports_last_edge_floor(self):
        h = LatencyHistogram(buckets=(0.01, 0.1))
        h.observe(5.0)
        assert h.quantile(0.5) == 0.1  # a floor, not a guess

    def test_prometheus_shape(self):
        h = LatencyHistogram(buckets=(0.01, 0.1))
        for v in (0.005, 0.05, 5.0):
            h.observe(v)
        d = h.to_dict()
        assert d["buckets"] == [(0.01, 1), (0.1, 2), (float("inf"), 3)]
        assert d["count"] == 3

    def test_rendered_histogram_and_phase_metrics(self):
        from hyperopt_tpu.observability import render_prometheus

        s = ServiceStats()
        s.record_request("suggest", seconds=0.02, study="a")
        s.record_phase("dispatch", 0.015)
        s.record_compile(1024, "cont+idx")
        text = render_prometheus(service=s)
        assert 'hyperopt_service_suggest_duration_seconds_bucket{le="+Inf"} 1' in text
        assert "hyperopt_service_suggest_duration_seconds_count 1" in text
        assert 'hyperopt_service_suggest_phase_seconds_total{phase="dispatch"}' in text
        assert ('hyperopt_compile_events_total{bucket="1024",'
                'families="cont+idx"} 1') in text


# ---------------------------------------------------------------------
# service integration: batching, pro-rata, compile attribution
# ---------------------------------------------------------------------


class TestServiceTracing:
    def _warmed_service(self, tracer=None, n_studies=2, root=None):
        """A service with ``n_studies`` studies past TPE startup (next
        suggest takes the fused device path)."""
        from hyperopt_tpu.service import OptimizationService

        svc = OptimizationService(
            root=root, batch_window=0.001, tracer=tracer
        )
        for i in range(n_studies):
            sid = f"s{i}"
            svc.create_study(sid, SPACE, seed=i + 1, algo_params=AP)
            for j in range(2):
                (t,) = svc.suggest(sid)
                svc.report(sid, t["tid"], loss=float(j))
        return svc

    def test_batched_pro_rata_sums_to_batch_total(self):
        """Drive one coalesced batch deterministically through the
        scheduler: each request's device spans carry the SHARED wall
        interval plus its pro-rata share, and the shares sum back to
        the batch total."""
        from hyperopt_tpu.algos import tpe_device
        from hyperopt_tpu.service.core import _PendingSuggest

        tracer = Tracer(sample=1.0)
        svc = self._warmed_service(tracer=tracer)
        try:
            tpe_device.reset_device_state()  # force a fresh compile
            traces, pendings = [], []
            for i in range(2):
                tr = tracer.begin()
                traces.append(tr)
                p = _PendingSuggest(svc.registry.get(f"s{i}"), 1)
                p.trace = tr
                p.popped_at = time.monotonic()
                pendings.append(p)
            svc.scheduler._attempt(pendings)
            assert all(p.done and p.error is None for p in pendings)

            total = None
            shares = []
            for tr in traces:
                spans = {s.name: s for s in tr.spans()}
                for name in ("device.dispatch", "device.readback"):
                    assert spans[name].attrs["batch_size"] == 2
                rb = spans["device.readback"]
                dp = spans["device.dispatch"]
                this_total = rb.attrs["device_total_s"]
                if total is None:
                    total = this_total
                # the shared interval is identical across the batch
                assert this_total == total
                shares.append(
                    dp.attrs["pro_rata_s"] + rb.attrs["pro_rata_s"]
                )
                # each request's share is 1/batch of the shared interval
                assert dp.attrs["pro_rata_s"] == pytest.approx(
                    dp.duration_s / 2, rel=1e-3
                )
            assert sum(shares) == pytest.approx(total, rel=1e-3)
        finally:
            _drain(svc)

    def test_forced_recompile_attaches_to_exactly_one_trace(self):
        """The batch leader pays for the XLA trace: the compile span
        lands on its trace and NO batch-mate's, tagged with the
        (trial-bucket, family) key."""
        from hyperopt_tpu.algos import tpe_device
        from hyperopt_tpu.service.core import _PendingSuggest

        tracer = Tracer(sample=1.0)
        svc = self._warmed_service(tracer=tracer)
        try:
            tpe_device.reset_device_state()  # guarantee a retrace
            before = svc.stats.n_compile_events
            traces, pendings = [], []
            for i in range(2):
                tr = tracer.begin()
                traces.append(tr)
                p = _PendingSuggest(svc.registry.get(f"s{i}"), 1)
                p.trace = tr
                p.popped_at = time.monotonic()
                pendings.append(p)
            svc.scheduler._attempt(pendings)
            assert all(p.done and p.error is None for p in pendings)
            assert svc.stats.n_compile_events > before

            compile_spans = {
                i: [s for s in tr.spans() if s.name == "compile"]
                for i, tr in enumerate(traces)
            }
            # exactly one trace carries the compile span(s): the leader
            assert len(compile_spans[0]) >= 1
            assert len(compile_spans[1]) == 0
            for s in compile_spans[0]:
                assert s.attrs["bucket"] > 0
                assert s.attrs["families"]
            # the stats counter uses the same (bucket, families) key
            key = (
                f"{compile_spans[0][0].attrs['bucket']}/"
                f"{compile_spans[0][0].attrs['families']}"
            )
            assert key in svc.stats.compile_events()
        finally:
            _drain(svc)

    def test_sampling_zero_service_is_noop(self, tmp_path):
        log = str(tmp_path / "never.jsonl")
        tracer = Tracer(path=log, sample=0.0)
        svc = self._warmed_service(tracer=tracer, n_studies=1)
        try:
            (t,) = svc.suggest("s0")
            svc.report("s0", t["tid"], loss=0.0)
            assert tracer.summary()["n_begun"] == 0
            assert not os.path.exists(log)
        finally:
            _drain(svc)

    def test_replay_is_tagged_and_excluded_from_latency(self, tmp_path):
        tracer = Tracer(sample=1.0)
        svc = self._warmed_service(
            tracer=tracer, n_studies=1, root=str(tmp_path / "root")
        )
        try:
            hist0 = svc.stats.histogram_dict()["count"]
            first = svc.suggest("s0", idempotency_key="RK")
            again = svc.suggest("s0", idempotency_key="RK")
            assert first == again
            # exactly one latency observation landed (the fresh one)
            assert svc.stats.histogram_dict()["count"] == hist0 + 1
            assert svc.stats.summary()["idempotent_replays"] == {
                "suggest": 1
            }
        finally:
            _drain(svc)

    def test_journal_fsync_span_present_for_keyed_suggest(self, tmp_path):
        log = str(tmp_path / "t.jsonl")
        tracer = Tracer(path=log, sample=1.0)
        svc = self._warmed_service(
            tracer=tracer, n_studies=1, root=str(tmp_path / "root")
        )
        try:
            svc.suggest("s0", idempotency_key="JK")
        finally:
            _drain(svc)
        records, _ = read_trace_log(log)
        keyed = [
            r for r in records
            if r["root"] == "service.suggest"
            and any(s["name"] == "journal.fsync" for s in r["spans"])
        ]
        assert keyed, "keyed suggest should carry a journal.fsync span"
        names = {s["name"] for s in keyed[-1]["spans"]}
        assert "store.insert" in names
        # the durable doc write: a segment group-commit on the default
        # backend, an atomic per-doc replace on the legacy one
        assert names & {"store.segment_append", "store.write_doc"}


# ---------------------------------------------------------------------
# HTTP header contract
# ---------------------------------------------------------------------


class TestHeaderContract:
    def test_header_adopted_and_echoed(self, tmp_path):
        import urllib.request

        from hyperopt_tpu.service import ServiceServer
        from hyperopt_tpu.service.core import (
            OptimizationService,
            encode_space,
        )

        log = str(tmp_path / "t.jsonl")
        svc = OptimizationService(tracer=Tracer(path=log, sample=1.0))
        server = ServiceServer(svc).start()
        try:
            my_id = "cafef00d" * 4  # caller-assigned trace id
            body = json.dumps({
                "study_id": "h1",
                "space_b64": encode_space(SPACE),
                "seed": 3,
                "algo": "tpe",
                "algo_params": AP,
            }).encode()
            req = urllib.request.Request(
                server.url + "/v1/studies", data=body,
                headers={
                    "Content-Type": "application/json",
                    tracing.TRACE_HEADER: my_id,
                },
                method="POST",
            )
            with urllib.request.urlopen(req, timeout=30) as r:
                assert r.status == 200
                # the server echoes the id it traced under
                assert r.headers[tracing.TRACE_HEADER] == my_id
        finally:
            server.stop()
        records, _ = read_trace_log(log)
        assert any(
            r["trace_id"] == my_id and r["root"] == "service.create_study"
            for r in records
        )

    def test_client_sends_ids_and_spans_ride_along(self, tmp_path):
        from hyperopt_tpu.service import ServiceClient, ServiceServer
        from hyperopt_tpu.service.core import OptimizationService

        server_log = str(tmp_path / "server.jsonl")
        client_log = str(tmp_path / "client.jsonl")
        svc = OptimizationService(
            tracer=Tracer(path=server_log, sample=1.0)
        )
        server = ServiceServer(svc).start()
        try:
            client = ServiceClient(
                server.url, tracer=Tracer(path=client_log, sample=1.0)
            )
            client.create_study("c1", SPACE, seed=5, algo_params=AP)
            (t,) = client.suggest("c1")
            client.report("c1", t["tid"], loss=1.0)
        finally:
            server.stop()
        server_recs, _ = read_trace_log(server_log)
        client_recs, _ = read_trace_log(client_log)
        server_ids = {r["trace_id"] for r in server_recs}
        # every client trace joined a server trace under the SAME id
        sugg = [
            r for r in client_recs
            if (r.get("root_attrs") or {}).get("route", "").endswith(
                "/suggest"
            )
        ]
        assert sugg and all(r["trace_id"] in server_ids for r in sugg)
        assert all(
            r["root"] == "client.request" for r in client_recs
        )


# ---------------------------------------------------------------------
# chaos correlation (satellite)
# ---------------------------------------------------------------------


class TestChaosCorrelation:
    def test_injection_log_carries_active_trace_id(self, tmp_path):
        from hyperopt_tpu.resilience.chaos import ChaosConfig, ChaosMonkey

        inj = str(tmp_path / "inj.jsonl")
        victim = tmp_path / "doc.json"
        victim.write_bytes(b"x" * 100)
        monkey = ChaosMonkey(ChaosConfig(
            seed=0, p_torn_doc=1.0, tear_kills_process=False,
            injection_log=inj,
        ))
        tracer = Tracer(sample=1.0)
        tr = tracer.begin()
        with tracing.use_trace(tr):
            monkey.maybe_torn_doc(str(victim), 7)
        # outside any trace, the stamp is null — still parseable
        victim.write_bytes(b"y" * 100)
        monkey.maybe_torn_doc(str(victim), 8)
        from hyperopt_tpu.resilience.chaos import parse_injection_log

        lines = parse_injection_log(open(inj, "rb").read())
        assert lines[0]["site"] == "torn_doc"
        assert lines[0]["trace_id"] == tr.trace_id
        assert lines[1]["trace_id"] is None


# ---------------------------------------------------------------------
# trace_report aggregation
# ---------------------------------------------------------------------


def _mk_record(trace_id, dur, spans, root="service.suggest", attrs=None):
    return {
        "trace_id": trace_id,
        "root": root,
        "root_attrs": attrs or {},
        "duration_s": dur,
        "start_unix": 0.0,
        "spans": [
            {"name": n, "id": i + 1, "parent": None,
             "t0_s": 0.0, "dur_s": d, "attrs": a}
            for i, (n, d, a) in enumerate(spans)
        ],
    }


class TestTraceReport:
    def test_coverage_phases_and_top_slowest(self):
        import trace_report

        good = _mk_record("t1", 0.1, [
            ("suggest.queue_wait", 0.05, {}),
            ("device.readback", 0.045, {"batch_size": 2}),
            ("journal.fsync", 0.004, {}),  # nested: not in coverage
        ])
        dark = _mk_record("t2", 0.2, [
            ("suggest.queue_wait", 0.02, {}),
        ])
        rep = trace_report.analyze([good, dark], min_coverage=0.9)
        assert rep["n_suggest_traces"] == 2
        assert rep["coverage"]["n_below_gate"] == 1
        assert not rep["ok"]
        assert rep["phases"]["journal.fsync"]["tiling"] is False
        assert rep["phases"]["suggest.queue_wait"]["count"] == 2
        top = rep["top_slowest"]
        assert top[0]["trace_id"] == "t2"
        assert top[0]["dominant"]["name"] == "suggest.queue_wait"

    def test_replay_traces_excluded_from_coverage(self):
        import trace_report

        replay = _mk_record("t3", 0.01, [], attrs={"replay": True})
        good = _mk_record("t4", 0.1, [
            ("suggest.queue_wait", 0.099, {}),
        ])
        rep = trace_report.analyze([replay, good], min_coverage=0.9)
        assert rep["n_replay_traces"] == 1
        assert rep["coverage"]["n_below_gate"] == 0
        assert rep["ok"]

    def test_unattributed_compile_fails_the_gate(self):
        import trace_report

        good = _mk_record("t5", 0.1, [
            ("suggest.queue_wait", 0.099, {}),
            ("compile", 0.0, {"bucket": 8, "families": "cont"}),
        ])
        rep = trace_report.analyze([good], min_coverage=0.9)
        assert rep["ok"] and rep["compile_events"]["n"] == 1
        bad = _mk_record("t6", 0.1, [
            ("suggest.queue_wait", 0.099, {}),
            ("compile", 0.0, {}),  # no (bucket, family) key
        ])
        rep = trace_report.analyze([good, bad], min_coverage=0.9)
        assert not rep["compile_events"]["attributed"]
        assert not rep["ok"]


# ---------------------------------------------------------------------
# race lint registration (satellite)
# ---------------------------------------------------------------------


def test_tracing_registered_and_race_clean():
    from hyperopt_tpu.analysis import discover_race_files, lint_races

    tracing_paths = [
        p for p in discover_race_files()
        if p.endswith(os.sep + "tracing.py")
    ]
    assert tracing_paths, "tracing.py must be race-linted"
    diags = lint_races(paths=tracing_paths)
    assert not diags, [str(d) for d in diags]
    # the annotations are real (not an empty file slipping through)
    src = open(tracing_paths[0]).read()
    assert "# guarded-by: _lock" in src
    assert "# guarded-by: _io_lock" in src
