"""Guards over the committed hardware-bench artifacts.

The round-4 verdict's top finding was headline numbers living only in
prose; these tests pin the committed artifacts to the claims README.md
and PARITY.md make from them (reference analog: the quality thresholds
hard-coded in hyperopt/tests/test_tpe.py are the reference's only
performance contract; ours is the captured-artifact contract).
"""

import json
import os

import pytest

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TPU = os.path.join(ROOT, "BENCH_TPU.json")
TPU_100K = os.path.join(ROOT, "BENCH_TPU_100k.json")

needs_tpu_json = pytest.mark.skipif(
    not os.path.exists(TPU), reason="no committed TPU bench artifact"
)


def _load(path):
    with open(path) as f:
        return json.load(f)


def _assert_null_with_reason_contract(d):
    """The VERDICT r6 #4 contract: a device-plane headline field is
    either measured (non-null, reason null) or null WITH a reason —
    never a silent 0.0 placeholder."""
    rate_fields = (
        "value", "vs_baseline", "achieved_tflops", "achieved_GBps",
        "roofline_pct", "roofline_pct_bw", "binding_ceiling",
    )
    for key in rate_fields:
        assert d.get(key) != 0.0, f"{key} is a 0.0 placeholder"
    if d["achieved_tflops"] is None:
        assert d.get("unmeasured_reason"), (
            "null achieved_tflops requires unmeasured_reason"
        )
    else:
        assert d.get("unmeasured_reason") is None
        assert d["binding_ceiling"] in ("hbm_bw", "flops")
        assert d["achieved_GBps"] > 0
        assert d["roofline_pct"] > 0 and d["roofline_pct_bw"] > 0
    if d["mfu_pct"] is None:
        assert d.get("unmeasured_reason") or d.get("mfu_pct_reason")


@needs_tpu_json
def test_headline_artifact_is_hardware_and_beats_north_star():
    d = _load(TPU)
    assert d["platform"] == "tpu"
    # BASELINE.md north star: >=1000x the CPU reference's EI-eval rate
    assert d["vs_baseline"] >= 1000.0, d["vs_baseline"]
    # measured capture: roofline attribution present and non-null
    _assert_null_with_reason_contract(d)
    assert d["mfu_pct"] is not None
    assert d["peaks"]["peak_hbm_GBps"] > 0
    # full scorer A/B on record: xla + both pallas modes at both
    # candidate counts and both history sizes
    ab = d["scorer_ab"]
    for scorer in ("xla", "pallas", "pallas_fma"):
        for h in (1000, 10000):
            for c in (8192, 65536):
                assert f"{scorer}_h{h}_c{c}_gei_s" in ab, (scorer, h, c)
    # end-to-end rates present (the BASELINE primary metric)
    assert d["suggests_per_sec_driver_loop"] > 0
    assert d["suggests_per_sec_batched"] > d["suggests_per_sec_driver_loop"]


REPRO = os.path.join(ROOT, "BENCH_TPU_repro.json")


@needs_tpu_json
@pytest.mark.skipif(
    not os.path.exists(REPRO), reason="no committed reproduction artifact"
)
def test_headline_reproduces_at_later_head():
    """An independent later-session capture must agree with the original.

    The round-4 complaint was unverifiable prose; the answer is not just
    one committed artifact but evidence the number is stable: a second
    run, after further commits, on a different day, within measurement
    noise of the first (scorer throughput is in-graph device timing, so
    the tolerance is tight; end-to-end rates vary with tunnel RTT and
    only need to stay in the >=1000x regime).
    """
    d0, d1 = _load(TPU), _load(REPRO)
    assert d1["platform"] == "tpu"
    assert d1["metric"] == d0["metric"]
    # device-timed scorer headline: within 10% of the original capture
    assert abs(d1["value"] - d0["value"]) / d0["value"] < 0.10
    # the north star must hold in BOTH captures independently
    assert d1["vs_baseline"] >= 1000.0
    assert d1["suggests_per_sec_driver_loop"] > 0
    # steady-state host traffic is a design property, not a timing: exact
    assert d1["host_bytes_per_suggest"] == d0["host_bytes_per_suggest"]


BATCHED = os.path.join(ROOT, "BENCH_TPU_batched.json")


@pytest.mark.skipif(
    not os.path.exists(BATCHED), reason="no committed batched-sweep artifact"
)
def test_batched_suggest_scales_with_k():
    d = _load(BATCHED)
    assert d["platform"] == "tpu"
    rows = sorted(d["rows"], key=lambda r: r["k"])
    assert len(rows) >= 3
    rates = [r["suggests_per_sec"] for r in rows]
    # batching must amortize per-dispatch overhead: monotone non-degrading
    # throughput in k (10% slack for timing noise) and a real win overall
    for a, b in zip(rates, rates[1:]):
        assert b > 0.9 * a, rates
    assert rates[-1] > 2 * rates[0], rates


SHARDED = os.path.join(ROOT, "BENCH_TPU_sharded.json")
SHARDED_QUICK = os.path.join(ROOT, "BENCH_TPU_sharded.quick.json")


@pytest.mark.skipif(
    not (os.path.exists(SHARDED) or os.path.exists(SHARDED_QUICK)),
    reason="no committed sharded-sweep artifact",
)
def test_sharded_sweep_artifact_covers_every_chip():
    """The ISSUE-11 acceptance artifact (BENCH_TPU_sharded.json, or its
    CPU-mesh .quick stand-in produced by ``bench.py --sharded
    --quick``): per-(k x mesh) rows with per-device limiter
    attribution.  Every guard is STRUCTURAL — arm/row coverage, device
    counts, dispatch accounting — never absolute milliseconds (sandbox
    latency swings ~30x between sessions)."""
    d = _load(SHARDED if os.path.exists(SHARDED) else SHARDED_QUICK)
    assert "sharded" in d["metric"]
    assert d["ok"] is True
    # both arms on record: the headline is the off-vs-mesh comparison
    arms = set(d["mesh_arms"])
    assert "off" in arms and len(arms) >= 2
    mesh_arm = next(a for a in d["mesh_arms"] if a != "off")
    dp, sp = (int(x) for x in mesh_arm.split("x"))
    assert dp * sp == d["n_devices"], (mesh_arm, d["n_devices"])
    rows_by_arm = {}
    for row in d["rows"]:
        rows_by_arm.setdefault(row["mesh"], []).append(row)
    # identical k coverage per arm — the comparison is row-for-row
    ks = {arm: sorted(r["k"] for r in rows) for arm, rows in
          rows_by_arm.items()}
    assert len(set(map(tuple, ks.values()))) == 1, ks
    for row in d["rows"]:
        assert row["suggests_per_sec"] > 0, row
        assert row["limiter"] in ("dispatch", "device_readback", "host")
        assert row["n_dispatches_observed"] > 0
    # the mesh arm's fused dispatches really spanned EVERY local chip,
    # and spanned them uniformly (one SPMD program, not a lopsided
    # single-chip fallback)
    for row in rows_by_arm[mesh_arm]:
        per_dev = row["per_device"]
        assert len(per_dev) == d["n_devices"], row["k"]
        counts = {v["n_dispatches"] for v in per_dev.values()}
        assert counts == {row["n_dispatches_observed"]}, (row["k"], counts)
    # the single-chip arm stays on one device
    for row in rows_by_arm["off"]:
        assert len(row["per_device"]) == 1, row["k"]


TRACE_SERVE = os.path.join(ROOT, "TRACE_SERVE.json")


@pytest.mark.skipif(
    not os.path.exists(TRACE_SERVE), reason="no committed trace artifact"
)
def test_trace_serve_artifact_attributes_the_tail():
    """The ISSUE-6 acceptance artifact: named tiling spans cover >= 90%
    of every sampled suggest's server wall-time, every XLA compile
    event is attributed to a trace id and (bucket, family) key, and
    sampling-off tracing is free (p50 within 5% of untraced)."""
    d = _load(TRACE_SERVE)
    assert d["metric"] == "trace_serve"
    assert d["ok"] is True
    assert d["n_suggest_traces"] > 0
    assert d["coverage"]["n_below_gate"] == 0
    assert d["coverage"]["min"] >= 0.9
    ce = d["compile_events"]
    assert ce["attributed"] is True
    for ev in ce["events"]:
        # bucket 0 is compile_key's documented fallback and still
        # attributed — mirror trace_report's own gate exactly
        assert ev["trace_id"] and ev["bucket"] is not None and ev["families"]
    # the tail is EXPLAINED: every slow trace names a dominant phase
    for t in d["top_slowest"]:
        assert t["dominant"] is not None and t["coverage"] >= 0.9
    if "overhead" in d:
        assert d["overhead"]["p50_regression_frac"] < 0.05


WARMUP_SERVE = os.path.join(ROOT, "WARMUP_SERVE.json")


@pytest.mark.skipif(
    not os.path.exists(WARMUP_SERVE),
    reason="no committed warmup artifact",
)
def test_warmup_serve_artifact_proves_the_closed_loop():
    """The ISSUE-10 acceptance artifact: AOT warmup covers >=95% of
    the campaign's bucket x family compile grid before /readyz, the
    warmed restart serves ZERO request-path compiles after ready
    (SL607 never breaches), the kill -9 restart's warmup replay is a
    small fraction of the cold run's compile cost, every containment
    fallback is trace-tagged served_cold, and the compile plane's
    steady-state overhead is <5%.  Every guard is STRUCTURAL
    (ratios/coverage/counts) — never absolute milliseconds: sandbox
    latency swings ~30x between sessions, but one run's cold and
    warmed measurements co-vary."""
    d = _load(WARMUP_SERVE)
    assert d["metric"] == "warmup_serve"
    assert d["ok"] is True
    # the committed artifact is the FULL capture (quick runs write
    # WARMUP_SERVE.quick.json and must never clobber this one)
    assert d["quick"] is False
    assert d["errors"] == []
    # warmup coverage of the campaign grid, before ready — and the
    # fraction is internally consistent with the independent fields
    cov = d["coverage"]
    assert cov["frac"] >= 0.95
    grid = set(cov["campaign_grid"])
    assert grid, "empty campaign grid proves nothing"
    assert cov["frac"] == pytest.approx(
        len(grid & set(cov["warmed_before_ready"])) / len(grid), abs=1e-4
    )
    # zero-cold-compile serving after the warmed restart's /readyz
    warmed = d["warmed"]
    assert warmed["n_cold_after_ready"] == 0
    assert warmed["sl607"]["breaches_total"] == 0
    assert warmed["sl607"]["status"] != "breach"
    assert warmed["warmup"]["finished"] is True
    assert warmed["warmup"]["warmed"] == warmed["warmup"]["total"]
    # the 503-body progress block the client logs from
    assert "warmed" in warmed["ready_doc_warmup"]
    # restart ratio: warmup replay work vs the cold compile bill —
    # the persistent cache must make the restart a FRACTION, never a
    # re-payment (ratio guard, no absolute seconds)
    ratio = d["restart_ratio"]
    assert ratio["warmed_over_cold"] is not None
    assert ratio["warmed_over_cold"] < ratio["gate"] <= 0.85
    assert ratio["cold_compile_s"] > 0
    # every remaining cold request is attributed: containment
    # fallbacks == served_cold-tagged traces (sampled at 1.0)
    sc = d["served_cold"]
    assert sc["attributed"] is True
    assert sc["n_trace_tagged"] == sc["n_fallbacks"]
    # the tail gate held on BOTH runs: warm p99 within the platform-
    # calibrated multiple of warm p50 (ok is None only when a run had
    # no warm traffic, which the campaign sizes preclude)
    assert d["cold"]["warm_tail"]["ok"] is True
    assert warmed["warm_tail"]["ok"] is True
    # compile-plane-on steady state within 5% of the off baseline
    assert d["overhead"]["p50_regression_frac"] < 0.05


@needs_tpu_json
@pytest.mark.skipif(
    not os.path.exists(TPU_100K), reason="no committed 100k artifact"
)
def test_100k_warmup_restamp_carries_reason():
    """The PR 10 re-stamp: the 50.7 s first-process warmup stays on
    record, and the persistent-cache warmed-restart field is null WITH
    a reason off-TPU (the PR 7 null contract), pointing at
    WARMUP_SERVE.json for the measured CPU-backend ratio."""
    d = _load(TPU_100K)
    assert d["compile_warmup_s"] > 0
    assert "compile_warmup_restart_s" in d
    if d["compile_warmup_restart_s"] is None:
        reason = d.get("compile_warmup_restart_reason")
        assert reason and "TPU" in reason
    else:
        assert d["compile_warmup_restart_s"] < d["compile_warmup_s"]


@needs_tpu_json
@pytest.mark.skipif(
    not os.path.exists(TPU_100K), reason="no committed 100k artifact"
)
def test_100k_headline_nulls_carry_a_reason():
    """The re-stamped 100k artifact: its device rate was unavailable at
    capture, so every rate-derived field must be null WITH a reason —
    the original 0.0 placeholders (VERDICT r6 #4) must never return."""
    d = _load(TPU_100K)
    _assert_null_with_reason_contract(d)
    assert d["value"] is None and d["vs_baseline"] is None
    assert d["achieved_tflops"] is None and d["mfu_pct"] is None
    assert "unavailable" in d["unmeasured_reason"]


@needs_tpu_json
@pytest.mark.skipif(
    not os.path.exists(TPU_100K), reason="no committed 100k artifact"
)
def test_host_traffic_flat_from_10k_to_100k_history():
    d10, d100 = _load(TPU), _load(TPU_100K)
    assert d100["platform"] == "tpu"
    assert d10["n_history"] == 10_000
    assert d100["n_history"] == 100_000
    # O(appended) steady state: bytes/suggest must not grow with history
    assert d100["host_bytes_per_suggest"] <= d10["host_bytes_per_suggest"]
    assert d100["host_transfer_ms_per_suggest"] < 5.0
    # the device-resident design's end-to-end payoff: driver-loop rate
    # within 20% of the 10k-history rate at 10x the history
    assert (
        d100["suggests_per_sec_driver_loop"]
        > 0.8 * d10["suggests_per_sec_driver_loop"]
    )


DEVICE_PROFILE = os.path.join(ROOT, "DEVICE_PROFILE.json")


STUDY_HEALTH = os.path.join(ROOT, "STUDY_HEALTH.json")


@pytest.mark.skipif(
    not os.path.exists(STUDY_HEALTH),
    reason="no committed study-health artifact",
)
def test_study_health_artifact_flags_every_fixture():
    """The ISSUE-8 acceptance artifact: every seeded degenerate fixture
    is flagged with its intended SH5xx rule, all healthy QUALITY.md
    domains report OK, the EI statistics provably add zero device
    dispatches (dispatch-count + one-trace-per-bucket assertions), and
    the measured suggest overhead is <5%."""
    d = _load(STUDY_HEALTH)
    assert d["metric"] == "study_health"
    assert d["ok"] is True
    # the committed artifact is the FULL capture (quick runs write
    # STUDY_HEALTH.quick.json and must never clobber this one)
    assert d["quick"] is False
    # healthy domains: the full QUALITY.md set, all OK
    assert set(d["healthy"]) == {
        "quadratic1", "branin", "gauss_wave2", "hartmann6"
    }
    for name, rec in d["healthy"].items():
        assert rec["state"] == "OK", (name, rec)
        assert rec["ok"] is True
    # one fixture per degenerate rule, each owned by its intended id
    intended = {v["intended_rule"] for v in d["fixtures"].values()}
    assert intended == {
        "SH501", "SH502", "SH503", "SH504", "SH505", "SH506"
    }
    for name, rec in d["fixtures"].items():
        assert rec["ok"] is True, (name, rec)
        assert rec["rule"] == rec["intended_rule"], (name, rec)
    # zero-dispatch contract: EI stats ride the existing fused readback
    zd = d["zero_dispatch"]
    assert zd["ok"] is True
    assert zd["extra_dispatches"] == 0
    assert zd["n_dispatches"] == zd["n_suggests"]
    assert zd["n_diag_snapshots"] == zd["n_suggests"]
    assert zd["retrace_violations"] == []
    # measured host-side overhead: suggest p50 within 5%
    assert d["overhead"] is not None
    assert d["overhead"]["p50_regression_frac"] < 0.05


SLO_SERVE = os.path.join(ROOT, "SLO_SERVE.json")


@pytest.mark.skipif(
    not os.path.exists(SLO_SERVE), reason="no committed SLO artifact"
)
def test_slo_serve_artifact_guards_every_rule():
    """The ISSUE-9 acceptance artifact: the healthy SLO-gated loadgen
    passes every SL6xx rule, one seeded forced-breach fixture per rule
    fires with its intended id (and ONLY it) and produces a parseable
    flight-recorder bundle carrying the breaching trace ids, the
    storage-plane counters reconcile exactly against trial counts, and
    the guardrails-on overhead is <5%."""
    d = _load(SLO_SERVE)
    assert d["metric"] == "slo_serve"
    assert d["ok"] is True
    # the committed artifact is the FULL capture (quick runs write
    # SLO_SERVE.quick.json and must never clobber this one)
    assert d["quick"] is False
    # healthy campaign: the full SL6xx catalog evaluated, nothing
    # breaching (no_data only where the rule's own gate says so).
    # Superset, not equality: the catalog grows (SL607 cold-compile
    # joined in PR 10) and an artifact captured before a rule existed
    # stays valid
    rules = {r["rule"]: r for r in d["healthy"]["rules"]}
    assert set(rules) >= {
        "SL601", "SL602", "SL603", "SL604", "SL605", "SL606"
    }
    for rule_id, r in rules.items():
        assert r["status"] != "breach", (rule_id, r)
        assert r["breaches_total"] == 0, (rule_id, r)
    # the latency rules carried real data in the healthy run
    assert rules["SL601"]["status"] == "ok"
    assert rules["SL602"]["status"] == "ok"
    # warm/cold split: the blended p99 is attributed, and the cold
    # (compile-touched) class is the slow one
    split = d["healthy"]["warm_cold_split"]
    assert split["ok"] is True
    assert split["n_warm"] > 0 and split["n_cold"] > 0
    assert split["cold_p99_ms"] > split["warm_p99_ms"]
    # storage-plane reconciliation: every fsync/doc-write/scan on the
    # loadgen path accounted against trial counts, exactly
    recon = d["healthy"]["reconciliation"]
    assert recon["ok"] is True and recon["mismatches"] == {}
    assert recon["observed"]["doc_writes"] == (
        2 * d["n_studies"] * d["n_trials_per_study"]
    )
    assert recon["observed"]["scans"] == d["n_studies"]
    # one seeded forced-breach fixture per rule, each OWNED by its
    # intended id with a validated bundle carrying the victims
    intended = {v["intended_rule"] for v in d["fixtures"].values()}
    assert intended == {
        "SL601", "SL602", "SL603", "SL604", "SL605", "SL606"
    }
    for name, rec in d["fixtures"].items():
        assert rec["ok"] is True, (name, rec)
        assert rec["breaching"] == [rec["intended_rule"]], (name, rec)
        assert rec["bundle"]["ok"] is True, (name, rec)
        assert rec["bundle"]["breaching_trace_ids_present"], (name, rec)
    assert d["recorder_roundtrip"]["ok"] is True
    # guardrails-on overhead: suggest p50 within 5%
    assert d["overhead"] is not None
    assert d["overhead"]["p50_regression_frac"] < 0.05


BENCH_SERVE = os.path.join(ROOT, "BENCH_SERVE.json")


@pytest.mark.skipif(
    not os.path.exists(BENCH_SERVE), reason="no committed serve artifact"
)
def test_bench_serve_artifact_carries_the_warm_cold_split():
    """The re-stamped BENCH_SERVE.json: the headline p99 is attributed
    (warm/cold split fields present and consistent), not blended-only."""
    d = _load(BENCH_SERVE)
    assert d["metric"] == "serve_loadgen"
    assert d["ok"] is True
    for key in (
        "suggest_warm_p50_ms", "suggest_warm_p99_ms",
        "suggest_cold_p50_ms", "suggest_cold_p99_ms",
        "n_warm_suggests", "n_cold_suggests",
    ):
        assert key in d, key
    assert (
        d["n_warm_suggests"] + d["n_cold_suggests"]
        == d["total_suggest_requests"]
    )
    # first touch is the expensive class — the attribution the split
    # exists to put on the record
    assert d["suggest_cold_p99_ms"] >= d["suggest_warm_p99_ms"]


@pytest.mark.skipif(
    not os.path.exists(DEVICE_PROFILE),
    reason="no committed device-profile artifact",
)
def test_device_profile_artifact_fully_attributed():
    """The ISSUE-7 acceptance artifact: a per-signature roofline table
    where EVERY dispatch reports a non-null binding ceiling and
    roofline_pct, a ceiling histogram that accounts for every dispatch,
    duty cycle and memory watermarks, and an observer-overhead check
    within 5%."""
    d = _load(DEVICE_PROFILE)
    assert d["metric"] == "device_profile"
    assert d["ok"] is True
    # the committed artifact is the FULL capture (a quick smoke writes
    # DEVICE_PROFILE.quick.json and must never clobber this one)
    assert d["quick"] is False
    assert d["overhead"] is not None
    assert d["n_dispatches"] >= 10
    assert d["unattributed_dispatches"] == 0
    # the ceiling histogram accounts for every dispatch
    assert sum(d["binding_ceiling_hist"].values()) == d["n_dispatches"]
    assert d["signatures"]
    for row in d["signatures"]:
        assert row["binding_ceiling"] in ("hbm_bw", "flops"), row
        assert row["roofline_pct"] is not None and row["roofline_pct"] > 0
        assert row["achieved_GBps"] is not None
        assert row["hbm_bytes_per_dispatch"] > 0
        assert row["flops_per_dispatch"] > 0
        assert row["ai_flops_per_byte"] > 0
    assert 0 < d["duty_cycle"] <= 1.0
    assert d["memory"]["live_buffer_highwater_bytes"] > 0
    assert d["peaks"]["peak_hbm_GBps"] > 0
    # XLA's own cost analysis cross-checks the analytical model on at
    # least one profiled signature
    assert any("xla" in row for row in d["signatures"])
    # observers-disabled overhead: suggest p50 within 5%
    if d.get("overhead"):
        assert d["overhead"]["p50_regression_frac"] < 0.05


# ---------------------------------------------------------------------
# BENCH_TPU_fused[.quick].json — the ISSUE-14 fused mega-kernel artifact
# ---------------------------------------------------------------------

FUSED = os.path.join(ROOT, "BENCH_TPU_fused.json")
FUSED_QUICK = os.path.join(ROOT, "BENCH_TPU_fused.quick.json")


@pytest.mark.skipif(
    not (os.path.exists(FUSED) or os.path.exists(FUSED_QUICK)),
    reason="no committed fused-kernel artifact",
)
def test_fused_kernel_artifact_structural_guards():
    """The ISSUE-14 acceptance artifact (BENCH_TPU_fused.json, or its
    interpret-mode .quick stand-in from ``bench.py --fused --quick``):
    bitwise fused==reference agreement across the shape grid INCLUDING
    the 100k-tiled case, trial-for-trial trajectory identity, a
    one-trace-per-bucket dispatch budget, and the 100k tile coverage —
    every guard STRUCTURAL (bitwise flags/counts/coverage), never
    absolute milliseconds (sandbox latency swings ~30x between
    sessions), and the TPU headline fields under the PR 7
    null-with-reason contract."""
    d = _load(FUSED if os.path.exists(FUSED) else FUSED_QUICK)
    assert d["metric"] == "fused_suggest_kernel"
    assert d["ok"] is True
    assert d["errors"] == []
    # the shape grid ran, and every default-mode (exact-draw) case is
    # BITWISE identical to the unfused reference — including the
    # 100k-history tiled case
    exact = [p for p in d["parity"] if not p["draw_in_kernel"]]
    assert len(exact) >= 6
    for p in exact:
        assert p["winner_bitwise_match"] is True, p["case"]
        assert p["winner_max_abs_err"] == 0.0, p["case"]
        assert p["diag_max_abs_err"] < 1e-3, p["case"]
    tiled = next(p for p in exact if p["case"] == "tiled_100k")
    assert tiled["k_total"] > 2 ** 17
    # the opt-in in-kernel-draw arm is on record with its documented
    # (ulp-class) tolerance — never asserted bitwise
    inkernel = [p for p in d["parity"] if p["draw_in_kernel"]]
    assert inkernel and all(
        p["winner_max_abs_err"] < 1e-5 for p in inkernel
    )
    # trajectory identity, trial for trial, at fixed seeds
    t = d["trajectory"]
    assert t["identical"] is True and t["first_divergence"] is None
    assert t["n_trials"] >= 30
    # dispatch accounting: the fused tier holds the one-trace-per-
    # (bucket, family) budget
    r = d["recompilation"]
    assert r["one_trace_per_bucket"] is True and r["violations"] == []
    assert r["n_traces"] >= len(r["buckets"]) >= 1
    # 100k tiling on record: the component axis is tiled (not a single
    # monolithic block) and the parameter block fits VMEM
    til = d["tiling_100k"]
    assert til["covered"] is True
    assert til["n_history"] == 100_000
    assert til["component_tiles"]["above"] >= 2
    assert til["params_vmem_frac_of_16mb"] < 0.5
    # headline: measured on TPU (with the >=10x target trackable) or
    # null WITH a reason pointing at the TPU capture path (PR 7)
    h = d["headline"]
    if h["value"] is None:
        assert h["unmeasured_reason"] and "TPU" in h["unmeasured_reason"]
    else:
        assert d["platform"] == "tpu"
        assert h["vs_unfused"] > 0
        assert h["unmeasured_reason"] is None


@needs_tpu_json
@pytest.mark.skipif(
    not os.path.exists(TPU_100K), reason="no committed 100k artifact"
)
def test_100k_null_reason_points_at_fused_artifact():
    """The ISSUE-14 re-stamp: the 100k headline's unmeasured_reason now
    names the fused artifact as the capture path instead of silently
    staying stale."""
    d = _load(TPU_100K)
    if d["value"] is None:
        assert "fused" in d["unmeasured_reason"]


@needs_tpu_json
def test_smoke_fma_defaults_carry_their_basis():
    """The ISSUE-14 satellite: both pallas_fma entry points stamp
    through the one resolve_fma resolver WITH the probe's measured
    basis, so two artifacts can no longer show unexplained
    contradictory defaults."""
    d10 = _load(TPU)
    basis = d10["smoke"]["pallas_fma_basis"]
    assert set(basis) == {"batched", "unbatched"}
    for v in basis.values():
        assert v in ("env", "measured", "other_kernel", "default_mxu")
    if os.path.exists(TPU_100K):
        d100 = _load(TPU_100K)
        b100 = d100["smoke"]["pallas_fma_basis"]
        # per-kernel defaults must AGREE between artifacts unless a
        # basis difference explains the split
        for kernel, field in (
            ("batched", "pallas_fma_default"),
            ("unbatched", "pallas_fma_default_unbatched"),
        ):
            if basis[kernel] == b100[kernel] == "measured":
                assert d10["smoke"][field] == d100["smoke"][field], kernel


# ---------------------------------------------------------------------
# FAILOVER_SERVE.json — the ISSUE-13 replica-plane failover artifact
# ---------------------------------------------------------------------

FAILOVER_SERVE = os.path.join(ROOT, "FAILOVER_SERVE.json")


@pytest.mark.skipif(
    not os.path.exists(FAILOVER_SERVE),
    reason="no committed failover artifact",
)
def test_failover_serve_artifact_proves_warm_takeover():
    """The ISSUE-13 acceptance artifact: >=8 studies across >=2
    replicas, the owning replica kill -9'd mid-campaign, every study
    it owned migrated after an ok + fsck-clean takeover, the migrated
    studies' first post-failover suggests hit ZERO request-path
    compiles (ledger pre-warm, proven by the survivor's cold-suggest
    counter deltas over the quiescent probe window), zero
    lost/duplicated trials, and every trajectory trial-for-trial
    identical to the fault-free single-replica twin.  Every guard is
    STRUCTURAL (counts/ratios/coverage) — never absolute milliseconds:
    sandbox latency swings ~30x between sessions."""
    d = _load(FAILOVER_SERVE)
    assert d["campaign"] == "failover_serve"
    assert d["ok"] is True
    # the committed artifact is the FULL capture (a quick smoke writes
    # FAILOVER_SERVE.quick.json and must never clobber this one)
    assert d["quick"] is False
    assert d["errors"] == []
    # scale floor: the acceptance's >=8 studies across >=2 replicas
    assert d["n_studies"] >= 8
    assert d["n_replicas"] >= 2
    assert len(d["study_ids"]) == d["n_studies"]
    # before the kill, BOTH replicas owned campaign studies (the
    # consistent-hash spread), and together they owned all of them
    owned = d["ownership_before_kill"]
    assert len(owned) == d["n_replicas"]
    assert all(owned.values())
    assert sorted(
        sid for sids in owned.values() for sid in sids
    ) == sorted(d["study_ids"])
    # the owner died for real, and every study it owned migrated
    assert d["victim_killed"] is True
    assert d["victim_owned"]
    assert d["migrated"] == d["victim_owned"]
    assert d["n_migrated"] == len(d["victim_owned"])
    # every takeover ok + fsck-clean, each migrated study accounted for
    assert d["all_takeovers_ok_and_fsck_clean"] is True
    by_study = {t["study_id"]: t for t in d["takeovers"]}
    for sid in d["victim_owned"]:
        rec = by_study[sid]
        assert rec["ok"] is True
        assert rec["fsck_clean"] is True
        assert rec["from_owner"] == d["victim"]
        assert rec["fence"] >= 1
    # warm failover: the pre-warm did real work with zero errors, and
    # the first post-failover suggests paid ZERO request-path compiles
    assert d["prewarm"]["error"] == 0
    assert d["prewarm"]["warm"] + d["prewarm"]["skipped"] >= 1
    cold = d["cold_suggest_delta_over_probe_window"]
    assert cold["n_cold_suggests"] == 0
    assert cold["n_cold_after_ready"] == 0
    # one first-suggest sample per migrated study was actually taken
    assert sorted(d["first_suggest_s"]) == sorted(d["victim_owned"])
    # exactly-once across the migration
    integ = d["integrity"]
    assert integ["lost_trials"] == 0
    assert integ["duplicated_trials"] == 0
    assert integ["incomplete_trials"] == 0
    assert integ["mismatched_studies"] == []
    assert d["trajectories_match_fault_free"] is True
    assert d["fsck_after_repair"]["clean"] is True


# ---------------------------------------------------------------------
# BENCH_STORE.json — the PR 16 segmented-trial-store artifact
# ---------------------------------------------------------------------

BENCH_STORE = os.path.join(ROOT, "BENCH_STORE.json")


@pytest.mark.skipif(
    not os.path.exists(BENCH_STORE), reason="no committed store artifact"
)
def test_store_artifact_proves_the_segment_log_wins():
    """The PR 16 acceptance artifact (``bench.py --store``): the
    segmented trial log vs the per-doc layout at 10k AND 100k trials.
    Every guard is a RATIO or COUNT — never absolute milliseconds
    (sandbox wall-clock swings ~30x between sessions)."""
    d = _load(BENCH_STORE)
    assert d["campaign"] == "store_bench"
    assert d["ok"] is True
    assert d["errors"] == []
    # the committed artifact is the FULL capture (quick runs write
    # BENCH_STORE.quick.json and must never clobber this one)
    assert d["quick"] is False
    assert set(d["scales"]) >= {10_000, 100_000}
    ratios = d["headline"]["fsync_ratio_doc_over_segment"]
    for n in d["scales"]:
        # the group-commit headline: >=10x fewer fsyncs per transition
        assert ratios[str(n)] >= 10.0, (n, ratios)
    rows = {(r["backend"], r["n_trials"]): r for r in d["rows"]}
    for n in d["scales"]:
        doc, seg = rows[("doc", n)], rows[("segment", n)]
        # per-doc pays one fsync per transition; the segment log folds
        # a whole batch into one
        assert doc["write"]["fsyncs_per_transition"] >= 1.0
        assert seg["write"]["fsyncs_per_transition"] <= 0.1
        assert seg["write"]["doc_writes"] == 0
        # group commit on record: far fewer write calls than records
        assert seg["write"]["segment_records"] == 2 * n
        assert seg["write"]["segment_appends"] * 10 <= (
            seg["write"]["segment_records"]
        )
        # zero O(N) scans anywhere on the segmented path
        assert seg["write"]["scans"] == 0
        assert seg["delta_refresh"]["scans"] == 0
        # refresh ∝ delta: the warm reader replays exactly the delta
        dr = seg["delta_refresh"]
        assert dr["replayed_records"] == dr["delta_docs"] == d["batch"]
        assert dr["full_replays"] == 0
        # recovery = replay the full log, losslessly
        assert seg["cold_open"]["replayed_records"] == 2 * n
        assert seg["cold_open"]["n_docs_recovered"] == n
        # compaction folds 2 records/trial to latest-per-tid, lossless
        comp = seg["compaction"]
        assert comp["n_docs_after"] == n + d["batch"]
        assert comp["records_before"] > comp["n_docs_after"]
        # the doc arm's delta refresh is the O(N) rescan the segment
        # path exists to dodge
        assert doc["delta_refresh"]["scan_entries"] >= n


# ---------------------------------------------------------------------------
# CONTROL_SERVE.json — the PR 19 closed-loop control-plane artifact
# ---------------------------------------------------------------------------

CONTROL_SERVE = os.path.join(ROOT, "CONTROL_SERVE.json")


@pytest.mark.skipif(
    not os.path.exists(CONTROL_SERVE),
    reason="no committed control artifact",
)
def test_control_serve_artifact_proves_the_closed_loop():
    """The PR 19 acceptance artifact: the SAME seeded shifting-load
    profile against a static server and a --self-tune server, with
    every gate green — self-tuned warm p99 no worse (within the
    platform tolerance recorded in the artifact), ZERO SL6xx breach
    transitions in the self-tuned arm, every applied decision present
    in both the decision journal and the knob-provenance journal, and
    the forced-breach fixture reverting to static within one window.
    Every guard is STRUCTURAL (gate booleans, counts, coverage) —
    never absolute milliseconds: sandbox latency swings ~30x between
    sessions."""
    d = _load(CONTROL_SERVE)
    assert d["metric"] == "control_serve_ab"
    assert d["ok"] is True
    gates = d["gates"]
    assert gates["p99_no_worse"] is True
    assert gates["zero_breach_transitions"] is True
    assert gates["decisions_journaled"] is True
    assert gates["controller_active"] is True
    assert gates["forced_breach_reverts"] is True
    assert gates["both_campaigns_complete"] is True
    # both arms ran the same declared multi-phase profile to completion
    assert len(d["profile"]) >= 2
    assert d["static"]["ok"] is True and d["self_tuned"]["ok"] is True
    # the controller actually closed the loop: proposals were applied,
    # and every applied decision is journal-accounted (no unlogged
    # actuation) — counts, not latencies
    audit = d["decision_audit"]
    assert audit["n_applied"] >= 1
    assert audit["missing_from_flight_ring"] == []
    assert audit["missing_from_knob_journal"] == []
    assert audit["n_controller_journal_writes"] >= audit["n_applied"]
    # zero breach transitions is recorded as a count, and the breaching
    # set at campaign end is empty
    assert d["self_tuned"]["breach_transitions"] == 0
    assert d["self_tuned"]["breaching"] == []
    # the forced-breach fixture: one clean evaluated cycle, then the
    # injected transition reverts within ONE window and freezes
    fb = d["forced_breach"]
    assert fb["cycle1"] == "evaluated"
    assert fb["knobs_moved_in_cycle1"] is True
    assert fb["cycle2"] == "reverted"
    assert fb["cycle3"] == "frozen"
    assert fb["windows_to_revert"] == 1
    assert fb["decision_actions"][-1] == "reverted"
    # p99 comparison is a ratio bound the artifact itself declares —
    # the guard checks consistency, never an absolute number
    tol = d["p99_tolerance_frac"]
    assert 0 < tol <= 0.5
    assert d["self_tuned"]["suggest_warm_p99_ms"] <= (
        d["static"]["suggest_warm_p99_ms"] * (1.0 + tol)
    )
