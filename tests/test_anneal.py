"""Annealing tests (reference parity: hyperopt/tests/test_anneal.py):
convergence-quality thresholds per domain + shrinkage behavior.
"""

import numpy as np
import pytest

from hyperopt_tpu import Domain, Trials, fmin
from hyperopt_tpu.algos import anneal, rand
from hyperopt_tpu.models import domains


@pytest.mark.parametrize(
    "name", ["quadratic1", "gauss_wave", "branin", "hartmann6", "q1_choice"]
)
def test_anneal_quality_on_domains(name):
    d = domains.get(name)
    trials = Trials()
    fmin(
        d.fn,
        d.space,
        algo=anneal.suggest,
        max_evals=d.quality_evals,
        trials=trials,
        rstate=np.random.default_rng(7),
        show_progressbar=False,
        verbose=False,
    )
    best = min(trials.losses())
    assert best < d.quality_threshold, (name, best, d.quality_threshold)


def test_anneal_shrinks_toward_incumbent():
    d = domains.get("quadratic1")
    trials = Trials()
    fmin(
        d.fn, d.space, algo=anneal.suggest, max_evals=120, trials=trials,
        rstate=np.random.default_rng(0), show_progressbar=False, verbose=False,
    )
    xs = np.array([m["vals"]["x"][0] for m in trials.miscs])
    # late proposals concentrate near the optimum (x=3) vs early ones
    early_spread = np.std(xs[:30])
    late_spread = np.std(xs[-30:])
    assert late_spread < early_spread
    assert abs(np.mean(xs[-30:]) - 3.0) < 1.0


def test_anneal_deterministic():
    d = domains.get("branin")
    trials = Trials()
    fmin(
        d.fn, d.space, algo=rand.suggest, max_evals=10, trials=trials,
        rstate=np.random.default_rng(0), show_progressbar=False, verbose=False,
    )
    domain = Domain(d.fn, d.space)
    a = anneal.suggest([100], domain, trials, seed=3)
    b = anneal.suggest([100], domain, trials, seed=3)
    assert a[0]["misc"]["vals"] == b[0]["misc"]["vals"]


def test_anneal_empty_history_uses_prior():
    d = domains.get("many_dists")
    domain = Domain(d.fn, d.space)
    trials = Trials()
    docs = anneal.suggest([0, 1, 2], domain, trials, seed=0)
    assert len(docs) == 3
    for doc in docs:
        v = doc["misc"]["vals"]
        assert 4 <= v["c"][0] <= 7
        assert v["a"][0] in (0, 1, 2)


def test_anneal_respects_bounds():
    d = domains.get("branin")
    trials = Trials()
    fmin(
        d.fn, d.space, algo=anneal.suggest, max_evals=150, trials=trials,
        rstate=np.random.default_rng(1), show_progressbar=False, verbose=False,
    )
    xs = [m["vals"]["x"][0] for m in trials.miscs]
    ys = [m["vals"]["y"][0] for m in trials.miscs]
    assert min(xs) >= -5.0 and max(xs) <= 10.0
    assert min(ys) >= 0.0 and max(ys) <= 15.0


def test_anneal_drops_nan_loss_trials():
    """A NaN-loss (diverged) trial must be excluded from the per-label
    observations — and shrink T with it — rather than occupying an
    arbitrary sort position (ADVICE r4 anneal.py:50)."""
    from hyperopt_tpu.base import JOB_STATE_DONE, STATUS_OK

    d = domains.get("quadratic1")
    domain = Domain(d.fn, d.space)
    trials = Trials()
    docs = []
    for i in range(6):
        loss = float("nan") if i == 2 else float(i)
        docs.append({
            "tid": i, "spec": None,
            "result": {"status": STATUS_OK, "loss": loss},
            "misc": {"tid": i, "cmd": None,
                     "idxs": {"x": [i]}, "vals": {"x": [float(i)]}},
            "state": JOB_STATE_DONE, "owner": None,
            "book_time": None, "refresh_time": None, "exp_key": None,
        })
    trials._insert_trial_docs(docs)
    trials.refresh()

    algo = anneal.AnnealingAlgo(domain, trials, seed=0)
    ls, tids, vals = algo.observations["x"]
    assert len(ls) == 5  # the NaN trial is gone
    assert 2 not in tids
    assert not np.isnan(ls).any()
    assert algo.shrinking("x") == 1.0 / (1.0 + 5 * algo.shrink_coef)
    # and suggest still works end to end
    out = anneal.suggest([100], domain, trials, seed=1)
    assert np.isfinite(out[0]["misc"]["vals"]["x"][0])
