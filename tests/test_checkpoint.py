"""Orbax structured trials checkpointing (SURVEY §7 option; the pickle
trials_save_file path keeps reference semantics and is tested in
test_fmin.py) + the ISSUE-3 hardening: torn-step fallback and the
fsync'd atomic pickle path."""

import glob
import os
import pickle

import numpy as np
import pytest

from hyperopt_tpu import Trials, fmin, hp
from hyperopt_tpu.algos import rand, tpe
from hyperopt_tpu.checkpoint import (
    TrialsCheckpointer,
    atomic_pickle_dump,
    is_orbax_path,
)


def _space():
    return {"x": hp.uniform("x", -5, 5)}


def _loss(d):
    return (d["x"] - 3) ** 2


class TestCheckpointer:
    def test_is_orbax_path(self):
        assert is_orbax_path("run.orbax")
        assert not is_orbax_path("run.pkl")
        assert not is_orbax_path("")

    def test_roundtrip_preserves_docs(self, tmp_path):
        trials = Trials()
        fmin(_loss, _space(), algo=rand.suggest, max_evals=12, trials=trials,
             rstate=np.random.default_rng(0), show_progressbar=False,
             verbose=False)
        ckpt = TrialsCheckpointer(str(tmp_path / "t.orbax"))
        assert ckpt.save(trials)
        restored = ckpt.restore()
        assert len(restored.trials) == 12
        # docs round-trip including datetimes and losses
        for a, b in zip(trials.trials, restored.trials):
            assert a["tid"] == b["tid"]
            assert a["result"]["loss"] == pytest.approx(b["result"]["loss"])
            assert a["book_time"] == b["book_time"]
        assert restored.argmin == trials.argmin

    def test_same_step_is_noop(self, tmp_path):
        trials = Trials()
        fmin(_loss, _space(), algo=rand.suggest, max_evals=5, trials=trials,
             rstate=np.random.default_rng(0), show_progressbar=False,
             verbose=False)
        ckpt = TrialsCheckpointer(str(tmp_path / "t.orbax"))
        assert ckpt.save(trials) is True
        assert ckpt.save(trials) is False  # no new trials -> no new step

    def test_retention(self, tmp_path):
        ckpt = TrialsCheckpointer(str(tmp_path / "t.orbax"), max_to_keep=2)
        trials = Trials()
        for n in (4, 8, 12):
            fmin(_loss, _space(), algo=rand.suggest, max_evals=n,
                 trials=trials, rstate=np.random.default_rng(0),
                 show_progressbar=False, verbose=False)
            ckpt.save(trials)
        assert ckpt.steps() == [2, 3]  # oldest step retired

    def test_in_place_result_mutation_triggers_save(self, tmp_path):
        """Async backends fill results into EXISTING docs (len unchanged);
        the change detector must still persist them."""
        from hyperopt_tpu.base import JOB_STATE_DONE, JOB_STATE_NEW

        trials = Trials()
        fmin(_loss, _space(), algo=rand.suggest, max_evals=6, trials=trials,
             rstate=np.random.default_rng(0), show_progressbar=False,
             verbose=False)
        ckpt = TrialsCheckpointer(str(tmp_path / "t.orbax"))
        # simulate an in-flight async doc
        doc = trials.trials[-1]
        doc["state"] = JOB_STATE_NEW
        saved_result = doc["result"]
        doc["result"] = {}
        trials.refresh()
        assert ckpt.save(trials) is True
        # worker completes the SAME doc in place
        doc["state"] = JOB_STATE_DONE
        doc["result"] = saved_result
        trials.refresh()
        assert ckpt.save(trials) is True  # would be lost by a len check
        restored = ckpt.restore()
        assert restored.trials[-1]["result"]["loss"] == pytest.approx(
            saved_result["loss"]
        )

    def test_restore_into_preserves_subclass(self, tmp_path):
        trials = Trials()
        fmin(_loss, _space(), algo=rand.suggest, max_evals=5, trials=trials,
             rstate=np.random.default_rng(0), show_progressbar=False,
             verbose=False)
        ckpt = TrialsCheckpointer(str(tmp_path / "t.orbax"))
        ckpt.save(trials)

        class MyTrials(Trials):
            pass

        mine = MyTrials()
        out = ckpt.restore(into=mine)
        assert out is mine
        assert isinstance(out, MyTrials)
        assert len(out.trials) == 5


class TestRestoreHardening:
    @staticmethod
    def _corrupt_step(directory, step):
        """Tear every payload file of one orbax step (a crash mid-write
        / truncated filesystem)."""
        step_dirs = [
            p for p in glob.glob(os.path.join(directory, "*"))
            if os.path.isdir(p) and os.path.basename(p).lstrip("0") in
            (str(step), "" if step == 0 else str(step))
        ]
        assert step_dirs, f"no step dir for {step} in {directory}"
        torn = 0
        for d in step_dirs:
            for root, _dirs, files in os.walk(d):
                for fn in files:
                    with open(os.path.join(root, fn), "wb") as f:
                        f.write(b"\x00torn checkpoint\x00")
                    torn += 1
        assert torn, "step had no files to corrupt"

    def test_corrupted_latest_step_falls_back(self, tmp_path):
        path = str(tmp_path / "t.orbax")
        ckpt = TrialsCheckpointer(path)
        trials = Trials()
        for n in (4, 9):
            fmin(_loss, _space(), algo=rand.suggest, max_evals=n,
                 trials=trials, rstate=np.random.default_rng(0),
                 show_progressbar=False, verbose=False)
            ckpt.save(trials)
        ckpt.close()
        steps = TrialsCheckpointer(path).steps()
        assert len(steps) == 2
        self._corrupt_step(path, steps[-1])
        restored = TrialsCheckpointer(path).restore()
        # fell back to the previous retained step (the 4-trial save)
        assert restored is not None
        assert len(restored.trials) == 4

    def test_explicit_step_request_still_raises(self, tmp_path):
        path = str(tmp_path / "t.orbax")
        ckpt = TrialsCheckpointer(path)
        trials = Trials()
        fmin(_loss, _space(), algo=rand.suggest, max_evals=4, trials=trials,
             rstate=np.random.default_rng(0), show_progressbar=False,
             verbose=False)
        ckpt.save(trials)
        ckpt.close()
        step = TrialsCheckpointer(path).steps()[-1]
        self._corrupt_step(path, step)
        with pytest.raises(Exception):
            TrialsCheckpointer(path).restore(step=step)

    def test_atomic_pickle_dump_is_loadable_and_replaces(self, tmp_path):
        path = str(tmp_path / "trials.pkl")
        atomic_pickle_dump({"a": 1}, path)
        with open(path, "rb") as f:
            assert pickle.load(f) == {"a": 1}
        atomic_pickle_dump({"b": 2}, path)
        with open(path, "rb") as f:
            assert pickle.load(f) == {"b": 2}
        # no temp litter
        assert sorted(os.listdir(tmp_path)) == ["trials.pkl"]


class TestFminIntegration:
    def test_fmin_saves_and_resumes(self, tmp_path):
        path = str(tmp_path / "run.orbax")
        fmin(_loss, _space(), algo=tpe.suggest, max_evals=8,
             trials_save_file=path, rstate=np.random.default_rng(1),
             show_progressbar=False, verbose=False)
        ckpt = TrialsCheckpointer(path)
        assert ckpt.restore() is not None
        assert len(ckpt.restore().trials) == 8
        # resume: a fresh fmin continues from the checkpoint
        fmin(_loss, _space(), algo=tpe.suggest, max_evals=15,
             trials_save_file=path, rstate=np.random.default_rng(1),
             show_progressbar=False, verbose=False)
        assert len(TrialsCheckpointer(path).restore().trials) == 15
