"""Plotting + observability smoke tests (reference parity: Agg smoke in
test_plotting.py / test_progress.py).
"""

import matplotlib

matplotlib.use("Agg")

import numpy as np
import pytest

from hyperopt_tpu import Trials, fmin, hp
from hyperopt_tpu.algos import rand
from hyperopt_tpu.observability import PhaseTimings, timed_suggest
from hyperopt_tpu.plotting import (
    main_plot_histogram,
    main_plot_history,
    main_plot_vars,
)


@pytest.fixture(scope="module")
def run_trials():
    trials = Trials()
    space = {
        "x": hp.uniform("x", -5, 5),
        "lr": hp.loguniform("lr", np.log(1e-6), np.log(1.0)),
    }
    fmin(
        lambda c: (c["x"] - 3) ** 2 + abs(np.log10(c["lr"]) + 3) * 0.1,
        space,
        algo=rand.suggest,
        max_evals=30,
        trials=trials,
        rstate=np.random.default_rng(0),
        show_progressbar=False,
        verbose=False,
    )
    return trials


def test_plot_history(run_trials):
    fig = main_plot_history(run_trials, do_show=False)
    assert fig is not None
    matplotlib.pyplot.close("all")


def test_plot_histogram(run_trials):
    fig = main_plot_histogram(run_trials, do_show=False)
    assert fig is not None
    matplotlib.pyplot.close("all")


def test_plot_vars_log_detection(run_trials):
    fig = main_plot_vars(run_trials, do_show=False, colorize_best=3)
    assert fig is not None
    axes = fig.get_axes()
    scales = {ax.get_title(): ax.get_xscale() for ax in axes if ax.get_title()}
    assert scales.get("lr") == "log"  # spans > 2 decades
    assert scales.get("x") == "linear"
    matplotlib.pyplot.close("all")


def test_plot_empty_trials():
    t = Trials()
    assert main_plot_histogram(t, do_show=False) is None
    assert main_plot_vars(t, do_show=False) is None
    matplotlib.pyplot.close("all")


def test_phase_timings():
    pt = PhaseTimings()
    with pt.phase("suggest"):
        pass
    pt.record("evaluate", 0.5)
    s = pt.summary()
    assert s["suggest"]["count"] == 1
    assert s["evaluate"]["total_s"] == 0.5


def test_timed_suggest_wrapper():
    pt = PhaseTimings()
    calls = []

    def algo(new_ids, domain, trials, seed):
        calls.append(1)
        return []

    wrapped = timed_suggest(algo, pt)
    wrapped([1], None, None, 0)
    assert calls == [1]
    assert pt.summary()["suggest"]["count"] == 1


def test_fminiter_records_timings():
    from hyperopt_tpu.fmin import FMinIter
    from hyperopt_tpu.base import Domain, Trials as T

    domain = Domain(lambda c: c["x"] ** 2, {"x": hp.uniform("x", -1, 1)})
    trials = T()
    it = FMinIter(
        rand.suggest, domain, trials, rstate=np.random.default_rng(0),
        max_evals=5, show_progressbar=False,
    )
    it.exhaust()
    s = it.timings.summary()
    assert s["suggest"]["count"] == 5
    assert s["evaluate"]["count"] >= 1


def test_plot_1d_attachment(run_trials):
    from hyperopt_tpu.plotting import main_plot_1D_attachment

    # attach a synthetic learning curve to every third trial
    for t in run_trials.trials[::3]:
        run_trials.trial_attachments(t)["curve"] = np.linspace(
            t["result"]["loss"] + 1.0, t["result"]["loss"], 20
        )
    fig = main_plot_1D_attachment(run_trials, "curve", do_show=False)
    assert fig is not None
    assert len(fig.gca().lines) == len(run_trials.trials[::3])
    matplotlib.pyplot.close("all")


def test_plot_1d_attachment_absent_warns(run_trials, caplog):
    from hyperopt_tpu.plotting import main_plot_1D_attachment

    with caplog.at_level("WARNING"):
        main_plot_1D_attachment(run_trials, "nope", do_show=False)
    assert any("nope" in r.message for r in caplog.records)
    matplotlib.pyplot.close("all")


def test_plot_1d_attachment_non_ok_trial_alpha_clamped(run_trials):
    # a failed trial with a loss worse than every OK loss must not
    # produce a negative alpha (regression: ValueError from matplotlib)
    from hyperopt_tpu.plotting import main_plot_1D_attachment

    bad = run_trials.trials[0]
    worst = max(t["result"]["loss"] for t in run_trials.trials)
    orig = dict(bad["result"])
    bad["result"] = {"status": "fail", "loss": worst + 100.0}
    try:
        run_trials.trial_attachments(bad)["curve2"] = np.linspace(1, 0, 5)
        fig = main_plot_1D_attachment(run_trials, "curve2", do_show=False)
        assert fig is not None
    finally:
        bad["result"] = orig
    matplotlib.pyplot.close("all")
