"""Mesh execution mode (ISSUE 11): the ``DeviceMesh`` abstraction, the
sharded-vs-single-chip determinism contract, the service scheduler's
mesh dispatch, topology-aware compile-ledger replay, and the per-device
telemetry split.

The suite runs on the conftest-forced 8-device virtual CPU mesh
(``XLA_FLAGS=--xla_force_host_platform_device_count=8``) — every mesh
code path is exercised in tier-1 without a TPU.

The determinism contract under test (docs/sharding.md):

- a DEGENERATE mesh (one device, or ``--mesh off``) dispatches
  **bit-for-bit** the single-chip program — same jit cache key, same
  docs;
- a REAL mesh keeps the fit/sample upstream replicated (pinned at the
  shard_map boundary — see ``tpe_device._sharded_pair_apply``) so the
  candidate draws are identical and the suggest trajectory is
  trial-for-trial equal to the unsharded one at the same seeds.
"""

import os
from functools import partial

import numpy as np
import pytest

import jax

from hyperopt_tpu import Domain, Trials, fmin, hp, space_eval
from hyperopt_tpu.algos import rand, tpe, tpe_device
from hyperopt_tpu.base import JOB_STATE_DONE, STATUS_OK
from hyperopt_tpu.parallel.sharding import (
    DeviceMesh,
    default_mesh,
    mesh_shape_str,
    resolve_mesh,
)

SPACE = {
    "x": hp.uniform("x", -5, 5),
    "c": hp.choice("c", ["a", "b"]),
    "w": hp.quniform("w", 0, 10, 1),
}
AP = {"n_startup_jobs": 4, "n_EI_candidates": 32}


def _objective(cfg):
    return (
        (cfg["x"] - 1.0) ** 2
        + (0.5 if cfg["c"] == "b" else 0.0)
        + 0.1 * cfg["w"]
    )


def _history_trials(seed=0, n=8, space=SPACE):
    domain = Domain(lambda c: 0.0, space)
    trials = Trials()
    rng = np.random.default_rng(seed)
    for i in range(n):
        docs = rand.suggest([i], domain, trials,
                            int(rng.integers(2 ** 31 - 1)))
        docs[0]["state"] = JOB_STATE_DONE
        docs[0]["result"] = {
            "status": STATUS_OK, "loss": float(rng.normal()),
        }
        trials.insert_trial_docs(docs)
        trials.refresh()
    return domain, trials


# ---------------------------------------------------------------------
# DeviceMesh units
# ---------------------------------------------------------------------


class TestDeviceMesh:
    def test_auto_uses_every_local_device(self):
        m = DeviceMesh.auto()
        assert m.n_devices == len(jax.devices())
        assert m.dp * m.sp == m.n_devices
        assert m.jax_mesh is not None
        assert m.topology()["mesh"] == m.shape_str
        assert m.topology()["device_count"] == m.n_devices

    def test_single_device_is_degenerate(self):
        m = DeviceMesh(devices=jax.devices()[:1])
        assert m.jax_mesh is None
        assert (m.dp, m.sp) == (1, 1)
        assert m.topology()["mesh"] == "off"
        assert resolve_mesh(m) is None

    def test_from_spec_grammar(self):
        assert DeviceMesh.from_spec(None) is None
        assert DeviceMesh.from_spec("off") is None
        assert DeviceMesh.from_spec("auto").n_devices == len(jax.devices())
        m = DeviceMesh.from_spec("4x2")
        assert (m.dp, m.sp) == (4, 2)
        assert DeviceMesh.from_spec("4,2") == m
        # a jax Mesh and a DeviceMesh pass through
        assert DeviceMesh.from_spec(m) is m
        via_jax = DeviceMesh.from_spec(default_mesh())
        assert via_jax.n_devices == len(jax.devices())
        with pytest.raises(ValueError):
            DeviceMesh.from_spec("3x9")  # no such device count
        with pytest.raises(ValueError):
            DeviceMesh.from_spec("banana")
        with pytest.raises(ValueError):
            DeviceMesh.from_spec("0x2")
        # a spec covering a SUBSET of the local chips is refused, not
        # silently truncated — idle chips would contradict the topology
        # identities (ledger fingerprint device_count, /v1/status)
        with pytest.raises(ValueError, match="covers 2 device"):
            DeviceMesh.from_spec("1x2")

    def test_labels_and_shape_str(self):
        m = DeviceMesh.from_spec("4x2")
        assert m.shape_str == "4x2"
        labels = m.device_labels()
        assert len(labels) == 8 and len(set(labels)) == 8
        assert all(":" in lb for lb in labels)
        assert mesh_shape_str(None) == "off"
        assert mesh_shape_str(m) == "4x2"
        assert mesh_shape_str(m.jax_mesh) == "4x2"


# ---------------------------------------------------------------------
# determinism: degenerate bit-for-bit, sharded trial-for-trial
# ---------------------------------------------------------------------


class TestMeshDeterminism:
    def test_degenerate_mesh_is_single_chip_program_bit_for_bit(self):
        """A one-device mesh resolves to mesh=None end to end: the
        prepared request list carries IDENTICAL statics (mesh=None) and
        maps to the SAME program key — not an equal-valued clone, the
        same jit cache entry — and the docs match exactly."""
        domain, trials = _history_trials()
        degenerate = DeviceMesh(devices=jax.devices()[:1])
        prep_none = tpe.suggest_prepare([100], domain, trials, 7, **AP)
        prep_deg = tpe.suggest_prepare(
            [100], domain, trials, 7, mesh=degenerate, **AP
        )
        assert (
            tpe_device.program_key(prep_none[0])
            == tpe_device.program_key(prep_deg[0])
        )
        for (_, _, st_a), (_, _, st_b) in zip(prep_none[0], prep_deg[0]):
            assert st_a == st_b
            assert st_a.get("mesh") is None
        a = tpe.suggest([100], domain, trials, 7, **AP)
        b = tpe.suggest([100], domain, trials, 7, mesh=degenerate, **AP)
        assert a[0]["misc"]["vals"] == b[0]["misc"]["vals"]
        c = tpe.suggest([100], domain, trials, 7, mesh="off", **AP)
        assert a[0]["misc"]["vals"] == c[0]["misc"]["vals"]

    def test_sharded_trajectory_equals_unsharded(self):
        """The 8-host-device CPU mesh: a full fmin trajectory through
        tpe.suggest(mesh=auto) is TRIAL-FOR-TRIAL identical to the
        unsharded run at the same seeds — the mesh changes the scoring
        layout, never the search."""
        def run(mesh):
            trials = Trials()
            fmin(
                _objective, SPACE,
                algo=partial(tpe.suggest, mesh=mesh, **AP),
                max_evals=16, trials=trials,
                rstate=np.random.default_rng(11), show_progressbar=False,
                verbose=False, max_speculation=0,
            )
            return [t["misc"]["vals"] for t in trials.trials]

        unsharded = run(None)
        sharded = run(DeviceMesh.auto())
        assert len(unsharded) == len(sharded) == 16
        for i, (u, s) in enumerate(zip(unsharded, sharded)):
            assert u == s, (i, u, s)

    def test_mixed_family_batched_dispatch_under_mesh(self):
        """Two studies with different spaces/history sizes, both
        prepared WITH the mesh, fused into one sharded device program:
        each study's docs equal its unbatched mesh suggest."""
        mesh = DeviceMesh.auto()
        space_b = {
            "lr": hp.loguniform("lr", np.log(1e-4), np.log(1.0)),
            "u": hp.randint("u", 5),
        }
        da, ta = _history_trials(seed=0, n=6)
        db, tb = _history_trials(seed=1, n=9, space=space_b)
        kw = dict(mesh=mesh, **AP)
        direct_a = tpe.suggest([60], da, ta, 123, **kw)
        direct_b = tpe.suggest([90, 91], db, tb, 456, **kw)

        prep_a = tpe.suggest_prepare([60], da, ta, 123, **kw)
        prep_b = tpe.suggest_prepare([90, 91], db, tb, 456, **kw)
        assert prep_a is not None and prep_b is not None
        # the prepared statics really carry the mesh (sharded program)
        assert any(
            st.get("mesh") is not None for _, _, st in prep_a[0]
        )
        res_a, res_b = tpe_device.multi_study_suggest_async(
            [prep_a[0], prep_b[0]]
        )
        batched_b = prep_b[1](res_b())
        batched_a = prep_a[1](res_a())
        for direct, batched in ((direct_a, batched_a),
                                (direct_b, batched_b)):
            assert len(direct) == len(batched)
            for d, b in zip(direct, batched):
                assert d["misc"]["vals"] == b["misc"]["vals"]

    def test_fusing_two_different_meshes_is_refused(self):
        """One fused program has ONE mesh: the replicated-pin
        containment cannot anchor to two.  Fusing groups prepared under
        different shapes must raise, not miscompile."""
        da, ta = _history_trials(seed=0, n=6)
        db, tb = _history_trials(seed=1, n=9)
        prep_a = tpe.suggest_prepare(
            [60], da, ta, 123, mesh=DeviceMesh.from_spec("4x2"), **AP
        )
        prep_b = tpe.suggest_prepare(
            [90], db, tb, 456, mesh=DeviceMesh.from_spec("2x4"), **AP
        )
        with pytest.raises(ValueError, match="different"):
            res = tpe_device.multi_study_suggest_async(
                [prep_a[0], prep_b[0]]
            )
            for r in res:
                r()

    def test_reset_device_state_clears_mesh_state(self):
        """DeviceRecovery's reset must drop mesh-scoped DeviceHistory
        mirrors and warm keys too — after a device error nothing may
        pin the failed chips."""
        mesh = DeviceMesh.auto()
        domain, trials = _history_trials(seed=3)
        prep = tpe.suggest_prepare([50], domain, trials, 5, mesh=mesh, **AP)
        tpe_device.multi_family_suggest_async(prep[0])()
        assert tpe_device.is_warm(prep[0])
        dh_mesh = tpe_device.device_history_for(
            trials, domain.space, mesh=resolve_mesh(mesh)
        )
        assert dh_mesh._n_synced > 0
        tpe_device.reset_device_state()
        assert not tpe_device.is_warm(prep[0])
        assert not tpe_device._cache  # all mirrors dropped, mesh ones too
        # and the path rebuilds cleanly after the reset
        docs = tpe.suggest([51], domain, trials, 6, mesh=mesh, **AP)
        assert docs and -5 <= docs[0]["misc"]["vals"]["x"][0] <= 5


# ---------------------------------------------------------------------
# the service scheduler dispatches through the mesh
# ---------------------------------------------------------------------


class TestServiceMesh:
    def _drive(self, svc, study_id, n):
        out = []
        for _ in range(n):
            (t,) = svc.suggest(study_id, n=1)
            out.append(t)
            point = space_eval(SPACE, t["vals"])
            svc.report(study_id, t["tid"], loss=_objective(point))
        return out

    def test_mesh_service_reproduces_serial_fmin(self):
        """The ISSUE-11 acceptance gate: with --mesh auto the scheduler
        dispatches ONE sharded program over all local chips and the
        single-study trajectory still reproduces serial
        fmin(tpe.suggest) trial-for-trial."""
        from hyperopt_tpu.service.core import OptimizationService

        trials = Trials()
        fmin(
            _objective, SPACE, algo=partial(tpe.suggest, **AP),
            max_evals=10, trials=trials,
            rstate=np.random.default_rng(42), show_progressbar=False,
            verbose=False, max_speculation=0,
        )
        ref = [
            {k: v[0] for k, v in t["misc"]["vals"].items() if len(v)}
            for t in trials.trials
        ]
        svc = OptimizationService(
            root=None, batch_window=0.001, mesh="auto",
            warmup=False, slo_enabled=False,
        )
        try:
            assert svc.mesh_label != "off"
            assert svc.mesh is not None
            svc.create_study("s", SPACE, seed=42, algo="tpe",
                             algo_params=AP)
            got = self._drive(svc, "s", 10)
            status = svc.service_status()
        finally:
            svc.close()
        for i, (rv, g) in enumerate(zip(ref, got)):
            assert rv.keys() == g["vals"].keys(), (i, rv, g)
            for k in rv:
                assert np.isclose(rv[k], g["vals"][k]), (i, k, rv, g)
        # the mesh surfaces on /v1/status and in the per-device split
        assert status["mesh"]["label"] == svc.mesh_label
        assert (
            status["mesh"]["topology"]["device_count"]
            == len(jax.devices())
        )
        per_dev = status["device"]["per_device"]
        assert len(per_dev) == len(jax.devices())

    def test_per_study_mesh_override_validated_at_create(self):
        """algo_params['mesh'] may opt out ('off') or restate the
        server mesh — a DIFFERENT mesh is a 400 at create (side-effect
        free), never a failed fused batch later."""
        from hyperopt_tpu.service.core import OptimizationService

        svc = OptimizationService(
            root=None, mesh="4x2", warmup=False, slo_enabled=False,
        )
        try:
            svc.create_study("opt-out", SPACE, seed=1, algo="tpe",
                             algo_params={"mesh": "off", **AP})
            svc.create_study("same", SPACE, seed=1, algo="tpe",
                             algo_params={"mesh": "4x2", **AP})
            with pytest.raises(ValueError, match="--mesh"):
                svc.create_study("other", SPACE, seed=1, algo="tpe",
                                 algo_params={"mesh": "2x4", **AP})
            assert "other" not in [
                s.study_id for s in svc.registry.studies()
            ]
        finally:
            svc.close()

    def test_mesh_off_is_default_and_unchanged(self):
        from hyperopt_tpu.service.core import OptimizationService

        svc = OptimizationService(
            root=None, warmup=False, slo_enabled=False
        )
        try:
            assert svc.mesh is None
            assert svc.mesh_label == "off"
            assert svc.service_status()["mesh"]["topology"] is None
        finally:
            svc.close()


# ---------------------------------------------------------------------
# topology-aware compile ledger
# ---------------------------------------------------------------------


class TestLedgerTopology:
    def test_fingerprint_carries_topology(self):
        from hyperopt_tpu import compile_ledger

        compile_ledger.set_topology(None)
        fp_off = compile_ledger.fingerprint()
        assert fp_off["topology"]["mesh"] == "off"
        assert fp_off["topology"]["device_count"] == len(jax.devices())
        try:
            compile_ledger.set_topology(DeviceMesh.auto())
            fp_mesh = compile_ledger.fingerprint()
            assert fp_mesh["topology"]["mesh"] == DeviceMesh.auto().shape_str
            assert fp_mesh != fp_off
        finally:
            compile_ledger.set_topology(None)

    def test_ledger_refuses_cross_topology_replay(self, tmp_path):
        """The pinned satellite gate: a ledger entry recorded under the
        single-chip topology is EXCLUDED from warmup once the process
        serves on a mesh (and vice versa) — a topology change must
        never warm the wrong program grid."""
        from hyperopt_tpu import compile_ledger

        domain, trials = _history_trials(seed=4)
        prep = tpe.suggest_prepare([70], domain, trials, 9, **AP)
        sig = tpe_device._multi_sig(prep[0])
        shapes = tpe_device.args_shapes([a for _, a, _ in prep[0]])

        ledger = compile_ledger.CompileLedger(
            str(tmp_path / "ledger.jsonl")
        )
        compile_ledger.set_topology(None)  # recorded single-chip
        try:
            ledger.record_compile(sig, shapes, duration_s=1.0)
            fp_off = compile_ledger.fingerprint()
            assert len(ledger.entries(current_fingerprint=fp_off)) == 1
            # same process, now serving on a mesh: the record is stale
            compile_ledger.set_topology(DeviceMesh.auto())
            fp_mesh = compile_ledger.fingerprint()
            assert ledger.entries(current_fingerprint=fp_mesh) == []
            # ... and a mesh-recorded program is stale for single-chip
            mesh = resolve_mesh(DeviceMesh.auto())
            prep_m = tpe.suggest_prepare(
                [71], domain, trials, 9, mesh=mesh, **AP
            )
            sig_m = tpe_device._multi_sig(prep_m[0])
            shapes_m = tpe_device.args_shapes(
                [a for _, a, _ in prep_m[0]]
            )
            rec_m = ledger.record_compile(sig_m, shapes_m, duration_s=1.0)
            assert len(ledger.entries(current_fingerprint=fp_mesh)) == 1
            compile_ledger.set_topology(None)
            off_keys = {
                r["replay_key"] for r in ledger.entries(
                    current_fingerprint=compile_ledger.fingerprint()
                )
            }
            assert rec_m["replay_key"] not in off_keys
            # the single-chip record is valid again under its topology
            assert len(off_keys) == 1
        finally:
            compile_ledger.set_topology(None)

    def test_mesh_record_replays_onto_live_mesh(self, tmp_path):
        """A SHARDED program's ledger record round-trips: the Mesh
        static serializes as its shape token and replay substitutes the
        live mesh — warmup warms the sharded grid, and the replayed
        request list maps to the exact program key the dispatch
        traced."""
        from hyperopt_tpu import compile_ledger

        mesh = resolve_mesh(DeviceMesh.auto())
        domain, trials = _history_trials(seed=5)
        prep = tpe.suggest_prepare(
            [80], domain, trials, 13, mesh=mesh, **AP
        )
        sig = tpe_device._multi_sig(prep[0])
        shapes = tpe_device.args_shapes([a for _, a, _ in prep[0]])
        ledger = compile_ledger.CompileLedger(
            str(tmp_path / "ledger.jsonl")
        )
        rec = ledger.record_compile(sig, shapes, duration_s=1.0)
        # the record is JSON-clean (reloadable) and mesh-tagged
        (reloaded,) = compile_ledger.CompileLedger(
            str(tmp_path / "ledger.jsonl")
        ).entries()
        assert reloaded["replay_key"] == rec["replay_key"]
        # no live mesh -> not replayable; matching mesh -> exact key
        assert compile_ledger.requests_from_record(reloaded) is None
        replay = compile_ledger.requests_from_record(reloaded, mesh=mesh)
        assert replay is not None
        assert (
            tpe_device.program_key(replay)
            == tpe_device.program_key(prep[0])
        )
        # a mismatched topology refuses
        wrong = default_mesh(shape=(2, 4))
        assert compile_ledger.requests_from_record(
            reloaded, mesh=wrong
        ) is None


# ---------------------------------------------------------------------
# per-device telemetry split
# ---------------------------------------------------------------------


class TestPerDeviceTelemetry:
    def test_mesh_dispatch_attributes_every_chip(self):
        from hyperopt_tpu import profiling
        from hyperopt_tpu.observability import DeviceStats, render_prometheus

        mesh = DeviceMesh.auto()
        domain, trials = _history_trials(seed=6)
        stats = DeviceStats()
        with profiling.DeviceProfiler(stats=stats):
            tpe.suggest([40], domain, trials, 2, mesh=mesh, **AP)
            tpe.suggest([41], domain, trials, 3, **AP)  # single-chip
        s = stats.summary()
        per_dev = s["per_device"]
        labels = mesh.device_labels()
        assert set(per_dev) == set(labels)
        # the mesh dispatch spanned all chips; the single-chip one only
        # the default device — which therefore has one more dispatch
        default = f"{jax.devices()[0].platform}:{jax.devices()[0].id}"
        others = [lb for lb in labels if lb != default]
        assert per_dev[default]["n_dispatches"] == 2
        assert all(per_dev[lb]["n_dispatches"] == 1 for lb in others)
        assert per_dev[default]["busy_s"] > per_dev[others[0]]["busy_s"]
        # exposition: labeled series present alongside the blend
        text = render_prometheus(device=stats)
        assert "hyperopt_device_duty_cycle " in text
        assert f'hyperopt_device_duty_cycle{{device="{default}"}}' in text
        assert (
            f'hyperopt_device_memory_highwater_bytes{{device="{default}"'
            in text
        )

    def test_mesh_peaks_scale_with_device_count(self):
        """The roofline ceilings of a mesh dispatch are the aggregate
        of the participating chips (ridge point unchanged)."""
        from hyperopt_tpu import profiling
        from hyperopt_tpu.observability import DeviceStats

        mesh = DeviceMesh.auto()
        domain, trials = _history_trials(seed=7)
        stats = DeviceStats()
        prof = profiling.DeviceProfiler(stats=stats)
        single = prof.peaks
        with prof:
            tpe.suggest([45], domain, trials, 2, mesh=mesh, **AP)
        rec = stats.last_record()
        assert rec is not None
        assert len(rec["devices"]) == mesh.n_devices
        # achieved_GBps is bytes/device_s; roofline_pct was computed
        # against the scaled ceiling — reconstruct and compare
        if rec["roofline_pct_bw"] is not None:
            scaled_bw = single["peak_hbm_GBps"] * mesh.n_devices
            expect = 100.0 * rec["achieved_GBps"] / scaled_bw
            assert rec["roofline_pct_bw"] == pytest.approx(
                expect, rel=1e-6
            )
