"""Core runtime tests (reference parity: hyperopt/tests/test_base.py):
Trials bookkeeping, Domain.evaluate, SONify, exception paths.
"""

import numpy as np
import pytest

from hyperopt_tpu import hp
from hyperopt_tpu.base import (
    JOB_STATE_DONE,
    JOB_STATE_ERROR,
    JOB_STATE_NEW,
    STATUS_FAIL,
    STATUS_OK,
    Ctrl,
    Domain,
    SONify,
    Trials,
    miscs_to_idxs_vals,
    miscs_update_idxs_vals,
    spec_from_misc,
    trials_from_docs,
    validate_loss_threshold,
    validate_timeout,
)
from hyperopt_tpu.exceptions import (
    AllTrialsFailed,
    InvalidLoss,
    InvalidResultStatus,
    InvalidTrial,
)


def make_trial(tid, loss=None, state=JOB_STATE_DONE, status=STATUS_OK, exp_key=None,
               labels=("x",), vals=(0.5,)):
    misc = {
        "tid": tid,
        "cmd": ("domain_attachment", "FMinIter_Domain"),
        "idxs": {lb: [tid] for lb in labels},
        "vals": {lb: [v] for lb, v in zip(labels, vals)},
    }
    result = {"status": status}
    if loss is not None:
        result["loss"] = loss
    return {
        "tid": tid,
        "spec": None,
        "result": result,
        "misc": misc,
        "state": state,
        "owner": None,
        "book_time": None,
        "refresh_time": None,
        "exp_key": exp_key,
    }


class TestSONify:
    def test_numpy_scalars(self):
        assert SONify(np.float64(1.5)) == 1.5
        assert type(SONify(np.float64(1.5))) is float
        assert SONify(np.int32(3)) == 3
        assert type(SONify(np.int32(3))) is int
        assert SONify(np.bool_(True)) is True

    def test_arrays_and_containers(self):
        assert SONify(np.array([1, 2, 3])) == [1, 2, 3]
        assert SONify(np.array(2.0)) == 2.0
        assert SONify({"a": np.int64(1), "b": (np.float32(0.5),)}) == {
            "a": 1,
            "b": (0.5,),
        }

    def test_unserializable_raises(self):
        with pytest.raises(TypeError):
            SONify(object())


class TestTrials:
    def test_new_trial_ids_monotonic(self):
        t = Trials()
        assert t.new_trial_ids(3) == [0, 1, 2]
        assert t.new_trial_ids(2) == [3, 4]

    def test_insert_and_refresh(self):
        t = Trials()
        docs = [make_trial(0, loss=1.0), make_trial(1, loss=0.5)]
        t.insert_trial_docs(docs)
        t.refresh()
        assert len(t) == 2
        assert t.losses() == [1.0, 0.5]
        assert t.statuses() == [STATUS_OK, STATUS_OK]

    def test_error_trials_filtered(self):
        t = Trials()
        t.insert_trial_docs([make_trial(0, loss=1.0), make_trial(1, state=JOB_STATE_ERROR)])
        t.refresh()
        assert len(t) == 1

    def test_best_trial_and_argmin(self):
        t = Trials()
        t.insert_trial_docs(
            [
                make_trial(0, loss=3.0, vals=(0.1,)),
                make_trial(1, loss=1.0, vals=(0.7,)),
                make_trial(2, loss=2.0, vals=(0.9,)),
            ]
        )
        t.refresh()
        assert t.best_trial["tid"] == 1
        assert t.argmin == {"x": 0.7}

    def test_all_trials_failed(self):
        t = Trials()
        t.insert_trial_docs([make_trial(0, status=STATUS_FAIL)])
        t.refresh()
        with pytest.raises(AllTrialsFailed):
            t.best_trial

    def test_exp_key_filtering(self):
        t = Trials(exp_key="mine")
        t._insert_trial_docs(
            [make_trial(0, loss=1.0, exp_key="mine"), make_trial(1, loss=0.1, exp_key="other")]
        )
        t.refresh()
        assert len(t) == 1
        assert t.best_trial["tid"] == 0

    def test_insert_wrong_exp_key_raises(self):
        t = Trials(exp_key="mine")
        with pytest.raises(InvalidTrial):
            t.insert_trial_doc(make_trial(0, loss=1.0, exp_key="other"))

    def test_invalid_trial_missing_key(self):
        t = Trials()
        doc = make_trial(0, loss=1.0)
        del doc["misc"]["cmd"]
        with pytest.raises(InvalidTrial):
            t.insert_trial_doc(doc)

    def test_tid_mismatch_raises(self):
        t = Trials()
        doc = make_trial(0, loss=1.0)
        doc["misc"]["tid"] = 5
        with pytest.raises(InvalidTrial):
            t.insert_trial_doc(doc)

    def test_count_by_state(self):
        t = Trials()
        t.insert_trial_docs(
            [make_trial(0, loss=1.0), make_trial(1, state=JOB_STATE_NEW, status="new")]
        )
        t.refresh()
        assert t.count_by_state_synced(JOB_STATE_DONE) == 1
        assert t.count_by_state_unsynced(JOB_STATE_NEW) == 1
        assert t.count_by_state_synced((JOB_STATE_NEW, JOB_STATE_DONE)) == 2

    def test_attachments(self):
        t = Trials()
        t.insert_trial_docs([make_trial(0, loss=1.0)])
        t.refresh()
        trial = t.trials[0]
        t.trial_attachments(trial)["blob"] = b"123"
        assert t.trial_attachments(trial)["blob"] == b"123"
        assert "blob" in t.trial_attachments(trial)
        del t.trial_attachments(trial)["blob"]
        assert "blob" not in t.trial_attachments(trial)

    def test_delete_all(self):
        t = Trials()
        t.insert_trial_docs([make_trial(0, loss=1.0)])
        t.refresh()
        t.attachments["g"] = 1
        t.delete_all()
        assert len(t) == 0
        assert t.attachments == {}

    def test_trials_from_docs(self):
        docs = [make_trial(0, loss=2.0)]
        t = trials_from_docs(docs)
        assert len(t) == 1

    def test_history_soa_cache(self):
        t = Trials()
        t.insert_trial_docs(
            [
                make_trial(0, loss=1.0, vals=(0.1,)),
                make_trial(1, loss=2.0, vals=(0.2,)),
                make_trial(2, status=STATUS_FAIL),
            ]
        )
        t.refresh()
        h = t.history
        assert list(h.loss_tids) == [0, 1]
        assert list(h.losses) == [1.0, 2.0]
        assert list(h.vals["x"]) == [0.1, 0.2]
        # cache object stable until new completions
        assert t.history is h

    def test_view_shares_docs(self):
        t = Trials()
        t.insert_trial_docs([make_trial(0, loss=1.0, exp_key=None)])
        t.refresh()
        v = t.view()
        assert len(v) == 1

    def test_average_best_error_no_variance(self):
        t = Trials()
        t.insert_trial_docs([make_trial(0, loss=3.0), make_trial(1, loss=1.5)])
        t.refresh()
        assert t.average_best_error() == 1.5


class TestMiscUtils:
    def test_miscs_roundtrip(self):
        miscs = [
            {"tid": 0, "cmd": None, "idxs": {"a": [0], "b": []}, "vals": {"a": [1.0], "b": []}},
            {"tid": 1, "cmd": None, "idxs": {"a": [1], "b": [1]}, "vals": {"a": [2.0], "b": [5]}},
        ]
        idxs, vals = miscs_to_idxs_vals(miscs)
        assert idxs == {"a": [0, 1], "b": [1]}
        assert vals == {"a": [1.0, 2.0], "b": [5]}

        blank = [
            {"tid": 0, "cmd": None, "idxs": {}, "vals": {}},
            {"tid": 1, "cmd": None, "idxs": {}, "vals": {}},
        ]
        miscs_update_idxs_vals(blank, idxs, vals)
        assert blank[0]["idxs"] == {"a": [0], "b": []}
        assert blank[1]["vals"] == {"a": [2.0], "b": [5]}

    def test_spec_from_misc(self):
        misc = {"tid": 3, "idxs": {"a": [3], "b": []}, "vals": {"a": [7.5], "b": []}}
        assert spec_from_misc(misc) == {"a": 7.5}

    def test_validate_timeout(self):
        validate_timeout(None)
        validate_timeout(5)
        for bad in (0, -1, True, "x"):
            with pytest.raises(Exception):
                validate_timeout(bad)

    def test_validate_loss_threshold(self):
        validate_loss_threshold(None)
        validate_loss_threshold(-3.5)
        for bad in (True, "x"):
            with pytest.raises(Exception):
                validate_loss_threshold(bad)


class TestDomain:
    def test_evaluate_scalar_result(self):
        space = {"x": hp.uniform("x", -1, 1)}
        domain = Domain(lambda cfg: cfg["x"] ** 2, space)
        trials = Trials()
        ctrl = Ctrl(trials)
        result = domain.evaluate({"x": 0.5}, ctrl)
        assert result == {"loss": 0.25, "status": STATUS_OK}

    def test_evaluate_dict_result(self):
        space = {"x": hp.uniform("x", -1, 1)}
        domain = Domain(
            lambda cfg: {"loss": abs(cfg["x"]), "status": STATUS_OK, "extra": 7},
            space,
        )
        result = domain.evaluate({"x": -0.25}, Ctrl(Trials()))
        assert result["loss"] == 0.25
        assert result["extra"] == 7

    def test_evaluate_conditional_space(self):
        space = hp.choice(
            "m",
            [
                {"kind": "a", "p": hp.uniform("p", 0, 1)},
                {"kind": "b", "q": hp.uniform("q", 0, 1)},
            ],
        )
        domain = Domain(
            lambda cfg: cfg["p"] if cfg["kind"] == "a" else 1 + cfg["q"], space
        )
        r0 = domain.evaluate({"m": 0, "p": 0.3}, Ctrl(Trials()))
        assert r0["loss"] == pytest.approx(0.3)
        r1 = domain.evaluate({"m": 1, "q": 0.4}, Ctrl(Trials()))
        assert r1["loss"] == pytest.approx(1.4)

    def test_invalid_status_raises(self):
        space = {"x": hp.uniform("x", 0, 1)}
        domain = Domain(lambda cfg: {"status": "bogus"}, space)
        with pytest.raises(InvalidResultStatus):
            domain.evaluate({"x": 0.1}, Ctrl(Trials()))

    def test_missing_loss_raises(self):
        space = {"x": hp.uniform("x", 0, 1)}
        domain = Domain(lambda cfg: {"status": STATUS_OK}, space)
        with pytest.raises(InvalidLoss):
            domain.evaluate({"x": 0.1}, Ctrl(Trials()))

    def test_attachments_from_result(self):
        space = {"x": hp.uniform("x", 0, 1)}
        domain = Domain(
            lambda cfg: {
                "loss": 0.0,
                "status": STATUS_OK,
                "attachments": {"art": b"bytes"},
            },
            space,
        )
        trials = Trials()
        tid = trials.new_trial_ids(1)[0]
        docs = trials.new_trial_docs(
            [tid],
            [None],
            [{"status": "new"}],
            [{"tid": tid, "cmd": None, "idxs": {"x": [tid]}, "vals": {"x": [0.5]}}],
        )
        trials.insert_trial_docs(docs)
        trials.refresh()
        ctrl = Ctrl(trials, current_trial=trials.trials[0])
        result = domain.evaluate({"x": 0.5}, ctrl)
        assert "attachments" not in result
        assert trials.trial_attachments(trials.trials[0])["art"] == b"bytes"

    def test_params_exposed(self):
        space = {"x": hp.uniform("x", 0, 1), "c": hp.choice("c", [1, 2])}
        domain = Domain(lambda cfg: 0.0, space)
        assert set(domain.params) == {"x", "c"}


class TestReviewRegressions:
    """Regressions from code review: NaN argmin, fast_isin bounds, history
    cache invalidation on in-place mutation."""

    def test_best_trial_skips_nan_losses(self):
        t = Trials()
        t.insert_trial_docs(
            [make_trial(0, loss=float("nan")), make_trial(1, loss=1.0)]
        )
        t.refresh()
        assert t.best_trial["tid"] == 1

    def test_all_nan_losses_raises(self):
        t = Trials()
        t.insert_trial_docs([make_trial(0, loss=float("nan"))])
        t.refresh()
        with pytest.raises(AllTrialsFailed):
            t.best_trial

    def test_fast_isin_out_of_range(self):
        from hyperopt_tpu.utils import fast_isin

        assert list(fast_isin(np.array([0]), np.array([-2, -1]))) == [False]
        assert list(fast_isin(np.array([-2, 0, 5]), np.array([-2, 3, 5]))) == [
            True,
            False,
            True,
        ]

    def test_history_invalidated_on_mutation(self):
        t = Trials()
        t.insert_trial_docs([make_trial(0, loss=1.0), make_trial(1, loss=2.0)])
        t.refresh()
        assert list(t.history.losses) == [1.0, 2.0]
        t.trials[1]["result"]["loss"] = 99.0
        t.refresh()
        assert list(t.history.losses) == [1.0, 99.0]


def test_trials_to_dataframe():
    import pandas as pd

    from hyperopt_tpu import Trials, fmin, hp
    from hyperopt_tpu.algos import rand

    space = hp.choice("k", [{"t": "a", "u": hp.uniform("u", 0, 1)},
                            {"t": "b", "v": hp.normal("v", 0, 1)}])
    trials = Trials()
    fmin(lambda c: c.get("u", 0.5) if c["t"] == "a" else abs(c["v"]),
         space, algo=rand.suggest, max_evals=10, trials=trials,
         rstate=np.random.default_rng(0), show_progressbar=False,
         verbose=False, return_argmin=False)
    df = trials.to_dataframe()
    assert isinstance(df, pd.DataFrame)
    assert len(df) == 10
    assert {"tid", "state", "status", "loss", "vals.k", "vals.u", "vals.v"} <= set(df.columns)
    # exactly one of u/v is active per row (conditional branches)
    active = df[["vals.u", "vals.v"]].notna().sum(axis=1)
    assert (active == 1).all()
    assert df["loss"].notna().all()


def test_pre_revision_pickle_resume_compat():
    # Trials pickled before the revision counter existed must still
    # refresh (trials_save_file resume path restores via pickle,
    # skipping __init__)
    import pickle

    from hyperopt_tpu import Trials, fmin, hp
    from hyperopt_tpu.algos import rand

    t = Trials()
    fmin(lambda c: c["x"] ** 2, {"x": hp.uniform("x", -1, 1)},
         algo=rand.suggest, max_evals=5, trials=t,
         rstate=np.random.default_rng(0), show_progressbar=False,
         verbose=False, return_argmin=False)
    blob = pickle.dumps(t)
    old = pickle.loads(blob)
    del old.__dict__["_revision"]  # simulate a pre-revision checkpoint
    # ... whose cache object also predates the newer attributes
    for attr in ("_seen_revision", "_loss_join_view"):
        old._history.__dict__.pop(attr, None)
    old = pickle.loads(pickle.dumps(old))  # round-trip the stripped form
    old.refresh()
    assert len(old.history.losses) == 5
    ok, ls = old.history.join_losses(old.history.loss_tids)
    assert ok.all() and len(ls) == 5
    old.refresh()
    assert old._revision >= 2


def test_history_cache_not_marked_fresh_after_failed_rebuild():
    # an exception mid-rebuild (malformed loss) must leave the cache
    # stale: the next access re-raises / recovers, never silently serves
    # pre-mutation arrays
    from hyperopt_tpu import Trials, fmin, hp
    from hyperopt_tpu.algos import rand

    t = Trials()
    fmin(lambda c: c["x"] ** 2, {"x": hp.uniform("x", -1, 1)},
         algo=rand.suggest, max_evals=4, trials=t,
         rstate=np.random.default_rng(0), show_progressbar=False,
         verbose=False, return_argmin=False)
    good_losses = list(t.history.losses)
    bad = t.trials[1]
    orig = bad["result"]["loss"]
    bad["result"]["loss"] = [1.0, 2.0]  # not float-convertible
    t._revision += 1  # mutation + sync point
    with pytest.raises(TypeError):
        t.history
    # still stale (not silently fresh): repairing the doc recovers fully
    bad["result"]["loss"] = orig
    t._revision += 1
    assert list(t.history.losses) == good_losses


def test_history_cache_atomic_under_malformed_misc():
    # an exception in the SoA extension walk (doc with idxs but missing
    # vals) must leave the PREVIOUS cache fully intact — no duplicated
    # columns, no stale-served fingerprint
    from hyperopt_tpu import Trials, fmin, hp
    from hyperopt_tpu.algos import rand
    from hyperopt_tpu.base import JOB_STATE_DONE, STATUS_OK

    t = Trials()
    fmin(lambda c: c["x"] ** 2, {"x": hp.uniform("x", -1, 1)},
         algo=rand.suggest, max_evals=4, trials=t,
         rstate=np.random.default_rng(0), show_progressbar=False,
         verbose=False, return_argmin=False)
    good_vals = list(t.history.vals["x"])
    # append a DONE doc whose misc is inconsistent: idxs present, vals empty
    bad = {
        "tid": 99, "spec": None,
        "result": {"status": STATUS_OK, "loss": 0.5},
        "misc": {"tid": 99, "cmd": None, "idxs": {"x": [99]}, "vals": {"x": []}},
        "state": JOB_STATE_DONE, "owner": None,
        "book_time": None, "refresh_time": None, "exp_key": None,
    }
    t._dynamic_trials.append(bad)
    with pytest.raises(Exception):
        t.refresh()
    # repeated reads keep raising (never silently fresh) ...
    with pytest.raises(Exception):
        t.history
    # ... and after removing the bad doc the cache is exactly the old one
    t._dynamic_trials.remove(bad)
    t.refresh()
    assert list(t.history.vals["x"]) == good_vals
    assert len(t.history.losses) == 4


def test_history_cache_atomic_under_noncastable_tid():
    # the FINAL materialization step (np.asarray of the idxs columns)
    # must also be pre-commit: a non-int-castable tid may not strand a
    # committed fingerprint over misaligned arrays
    from hyperopt_tpu import Trials, fmin, hp
    from hyperopt_tpu.algos import rand
    from hyperopt_tpu.base import JOB_STATE_DONE, STATUS_OK

    t = Trials()
    fmin(lambda c: c["x"] ** 2, {"x": hp.uniform("x", -1, 1)},
         algo=rand.suggest, max_evals=3, trials=t,
         rstate=np.random.default_rng(0), show_progressbar=False,
         verbose=False, return_argmin=False)
    good = list(t.history.vals["x"])
    bad = {
        "tid": 77, "spec": None,
        "result": {"status": STATUS_OK, "loss": 0.1},
        "misc": {"tid": 77, "cmd": None,
                 "idxs": {"x": [None]}, "vals": {"x": [0.5]}},
        "state": JOB_STATE_DONE, "owner": None,
        "book_time": None, "refresh_time": None, "exp_key": None,
    }
    t._dynamic_trials.append(bad)
    with pytest.raises(Exception):
        t.refresh()
    with pytest.raises(Exception):
        t.history  # still raising, never silently misaligned
    t._dynamic_trials.remove(bad)
    t.refresh()
    assert list(t.history.vals["x"]) == good
    assert len(t.history.loss_tids) == len(t.history.idxs["x"]) == 3


def test_package_export_surface():
    """The reference's package-level names resolve on hyperopt_tpu —
    the drop-in-import contract (hyperopt/__init__.py exports, SURVEY
    §2 #23), including the functools.partial re-export."""
    import hyperopt_tpu as h

    for name in (
        "fmin", "hp", "tpe", "atpe", "rand", "anneal", "mix", "Trials",
        "space_eval", "pyll", "partial", "trials_from_docs", "Domain",
        "FMinIter", "STATUS_OK", "STATUS_FAIL", "STATUS_STRINGS",
        "JOB_STATE_NEW", "JOB_STATE_DONE", "JOB_STATE_ERROR",
        "no_progress_loss",
    ):
        assert hasattr(h, name), name
    for name in h.__all__:
        assert hasattr(h, name), f"__all__ lists missing name {name}"
