"""Test configuration: force the suite onto a virtual 8-device CPU mesh.

This is the prescribed way to test TPU sharding logic without a pod
(SURVEY.md §4 pattern 3).  Two subtleties in this environment:

- ``XLA_FLAGS`` must be in the env before the CPU backend initializes.
- The axon TPU plugin's sitecustomize calls
  ``jax.config.update("jax_platforms", "axon,cpu")`` in *every* Python
  process, clobbering the ``JAX_PLATFORMS`` env var — so we must update
  the config back to ``cpu`` here, before any JAX operation runs.
  (Running the suite through the remote-TPU tunnel makes every jit
  compile a network round-trip: 30x slower and single-process-locked.)

Benchmarks (bench.py) run separately and do use the real TPU chip.
"""

import os

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402  (import after env setup, before any test imports)

jax.config.update("jax_platforms", "cpu")
