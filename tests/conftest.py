"""Test configuration: force the suite onto a virtual 8-device CPU mesh.

This is the prescribed way to test TPU sharding logic without a pod
(SURVEY.md §4 pattern 3).  Two subtleties in this environment:

- ``XLA_FLAGS`` must be in the env before the CPU backend initializes.
- The axon TPU plugin's sitecustomize calls
  ``jax.config.update("jax_platforms", "axon,cpu")`` in *every* Python
  process, clobbering the ``JAX_PLATFORMS`` env var — so we must update
  the config back to ``cpu`` here, before any JAX operation runs.
  (Running the suite through the remote-TPU tunnel makes every jit
  compile a network round-trip: 30x slower and single-process-locked.)

TPU smoke tier: ``HYPEROPT_TPU_TESTS=1 pytest -m tpu`` keeps the real
TPU backend and runs only the ``tpu``-marked hardware tests (Mosaic
lowering checks that ``interpret=True`` cannot catch).  bench.py runs
the same smoke in-process before timing.  Without the env var the suite
stays on the CPU mesh and ``tpu``-marked tests self-skip.
"""

import os

import pytest

_TPU_MODE = os.environ.get("HYPEROPT_TPU_TESTS") == "1"

if not _TPU_MODE:
    _flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in _flags:
        os.environ["XLA_FLAGS"] = (
            _flags + " --xla_force_host_platform_device_count=8"
        ).strip()
    os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402  (import after env setup, before any test imports)

if not _TPU_MODE:
    jax.config.update("jax_platforms", "cpu")


def pytest_collection_modifyitems(config, items):
    if _TPU_MODE:
        # TPU mode never set up the 8-device CPU mesh the rest of the
        # suite assumes — run ONLY tpu-marked items even without -m tpu
        deselected = [it for it in items if "tpu" not in it.keywords]
        if deselected:
            config.hook.pytest_deselected(items=deselected)
            items[:] = [it for it in items if "tpu" in it.keywords]
        return
    skip = pytest.mark.skip(reason="requires a real TPU backend "
                            "(HYPEROPT_TPU_TESTS=1 pytest -m tpu)")
    for item in items:
        if "tpu" in item.keywords:
            item.add_marker(skip)
