"""Test configuration.

Runs the suite on a virtual 8-device CPU mesh (the prescribed way to test
TPU sharding logic without a pod); must set env vars before jax initializes.
Benchmarks (bench.py) run separately on the real TPU chip.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()
