"""Fused scorer kernels vs the exact lpdf reference (ops.score +
ops.pallas_gmm in interpreter mode)."""

import numpy as np
import pytest

import jax.numpy as jnp

from hyperopt_tpu.ops import gmm as gmm_ops
from hyperopt_tpu.ops.pallas_gmm import pair_score_pallas
from hyperopt_tpu.ops.score import pair_params, pair_score


def make_pair(K=37, seed=0, padded_tail=5):
    rng = np.random.default_rng(seed)
    def mk():
        w = rng.uniform(0.1, 1.0, K).astype(np.float32)
        if padded_tail:
            w[-padded_tail:] = 0.0
        w /= w.sum()
        mu = rng.normal(0, 2, K).astype(np.float32)
        s = rng.uniform(0.5, 2.0, K).astype(np.float32)
        return w, mu, s
    return mk(), mk()


def exact_diff(z, below, above):
    inf = np.float32(np.inf)
    args = (np.float32(-inf), inf, np.float32(0.0), False, False)
    return np.asarray(gmm_ops.gmm_lpdf(z, *below, *args)) - np.asarray(
        gmm_ops.gmm_lpdf(z, *above, *args)
    )


@pytest.mark.parametrize("C,K", [(100, 8), (1000, 37), (257, 130)])
def test_xla_scorer_matches_exact(C, K):
    below, above = make_pair(K=K, padded_tail=min(3, K - 1))
    z = np.random.default_rng(1).uniform(-4, 4, C).astype(np.float32)
    ref = exact_diff(z, below, above)
    got = np.asarray(pair_score(z, pair_params(*below, *above), K))
    np.testing.assert_allclose(got, ref, atol=5e-5)


def test_xla_scorer_chunking_invariant():
    below, above = make_pair(K=21)
    z = np.random.default_rng(2).uniform(-4, 4, 999).astype(np.float32)
    P = pair_params(*below, *above)
    a = np.asarray(pair_score(z, P, 21, chunk=64))
    b = np.asarray(pair_score(z, P, 21, chunk=4096))
    np.testing.assert_allclose(a, b, atol=1e-6)


@pytest.mark.parametrize("C,K,tc,tk", [(100, 37, 32, 128), (600, 300, 256, 256)])
def test_pallas_scorer_matches_exact(C, K, tc, tk):
    below, above = make_pair(K=K, padded_tail=4)
    z = np.random.default_rng(3).uniform(-4, 4, C).astype(np.float32)
    ref = exact_diff(z, below, above)
    got = np.asarray(
        pair_score_pallas(z, pair_params(*below, *above), K, tc=tc, tk=tk, interpret=True)
    )
    np.testing.assert_allclose(got, ref, atol=5e-5)


def test_pallas_handles_component_padding():
    # K not a multiple of the tile: kernel pads with -inf logcoef
    below, above = make_pair(K=137, padded_tail=10)
    z = np.random.default_rng(4).uniform(-4, 4, 64).astype(np.float32)
    ref = exact_diff(z, below, above)
    got = np.asarray(
        pair_score_pallas(
            z, pair_params(*below, *above), 137, tc=64, tk=128, interpret=True
        )
    )
    np.testing.assert_allclose(got, ref, atol=5e-5)


def test_scorer_selection_env(monkeypatch):
    from hyperopt_tpu.algos.tpe import _use_pallas

    monkeypatch.setenv("HYPEROPT_TPU_SCORER", "exact")
    assert _use_pallas() == "exact"
    monkeypatch.delenv("HYPEROPT_TPU_SCORER")
    assert _use_pallas() in ("xla", "pallas")


def test_effective_scorer_crossover(monkeypatch):
    from hyperopt_tpu.ops.score import PALLAS_MIN_K, effective_scorer

    monkeypatch.delenv("HYPEROPT_TPU_SCORER", raising=False)
    # auto-selected pallas demotes to xla below the VMEM-spill crossover
    assert effective_scorer("pallas", PALLAS_MIN_K - 1) == "xla"
    assert effective_scorer("pallas", PALLAS_MIN_K) == "pallas"
    assert effective_scorer("xla", 10**6) == "xla"
    # an explicit force is honored verbatim at any size
    monkeypatch.setenv("HYPEROPT_TPU_SCORER", "pallas")
    assert effective_scorer("pallas", 8) == "pallas"


def test_pallas_batched_matches_single():
    rng = np.random.default_rng(5)
    L, C, K = 3, 200, 50
    zs, Ps, singles = [], [], []
    for l in range(L):
        below, above = make_pair(K=K, seed=l, padded_tail=3)
        z = rng.uniform(-4, 4, C).astype(np.float32)
        P = pair_params(*below, *above)
        zs.append(z)
        Ps.append(np.asarray(P))
        singles.append(np.asarray(pair_score_pallas(z, P, K, interpret=True)))
    from hyperopt_tpu.ops.pallas_gmm import pair_score_pallas_batched

    batched = np.asarray(
        pair_score_pallas_batched(
            np.stack(zs), np.stack(Ps), K, tc=64, tk=128, interpret=True
        )
    )
    np.testing.assert_allclose(batched, np.stack(singles), atol=2e-5)


@pytest.mark.parametrize("C,K,tc,tk", [(100, 37, 32, 128), (600, 300, 256, 256)])
def test_pallas_fma_variant_matches_exact(C, K, tc, tk):
    # the VPU-FMA quadratic evaluation must be numerically equivalent to
    # the MXU dot path (different summation order, same f32 math)
    below, above = make_pair(K=K, padded_tail=4)
    z = np.random.default_rng(6).uniform(-4, 4, C).astype(np.float32)
    ref = exact_diff(z, below, above)
    got = np.asarray(
        pair_score_pallas(
            z, pair_params(*below, *above), K, tc=tc, tk=tk,
            interpret=True, fma=True,
        )
    )
    np.testing.assert_allclose(got, ref, atol=5e-5)


def test_pallas_fma_batched_matches_mxu():
    from hyperopt_tpu.ops.pallas_gmm import pair_score_pallas_batched

    rng = np.random.default_rng(7)
    L, C, K = 3, 200, 50
    zs, Ps = [], []
    for l in range(L):
        below, above = make_pair(K=K, seed=l, padded_tail=3)
        zs.append(rng.uniform(-4, 4, C).astype(np.float32))
        Ps.append(np.asarray(pair_params(*below, *above)))
    z = np.stack(zs)
    P = np.stack(Ps)
    mxu = np.asarray(pair_score_pallas_batched(z, P, K, interpret=True, fma=False))
    fma = np.asarray(pair_score_pallas_batched(z, P, K, interpret=True, fma=True))
    np.testing.assert_allclose(fma, mxu, atol=5e-5)


def test_pallas_fma_env_default(monkeypatch):
    from hyperopt_tpu.ops import pallas_gmm

    monkeypatch.delenv("HYPEROPT_TPU_PALLAS_FMA", raising=False)
    assert pallas_gmm.resolve_fma("batched") is False
    monkeypatch.setenv("HYPEROPT_TPU_PALLAS_FMA", "1")
    assert pallas_gmm.resolve_fma("batched") is True
    monkeypatch.setenv("HYPEROPT_TPU_PALLAS_FMA", "0")
    assert pallas_gmm.resolve_fma("batched") is False
    # the back-compat alias routes through the same resolver
    monkeypatch.setenv("HYPEROPT_TPU_PALLAS_FMA", "1")
    assert pallas_gmm._default_fma() is True
    assert pallas_gmm._default_fma(batched=False) is True


def test_fma_measured_default_precedence(monkeypatch):
    from hyperopt_tpu.ops import pallas_gmm

    monkeypatch.delenv("HYPEROPT_TPU_PALLAS_FMA", raising=False)
    # monkeypatch BOTH globals first so set_default_fma's writes are
    # rolled back at teardown (kernel="both" touches the unbatched one)
    monkeypatch.setattr(pallas_gmm, "_fma_measured_default", None)
    monkeypatch.setattr(pallas_gmm, "_fma_measured_default_unbatched", None)
    assert pallas_gmm.resolve_fma("batched") is False
    assert pallas_gmm.resolve_fma("unbatched") is False
    pallas_gmm.set_default_fma(True)
    assert pallas_gmm.resolve_fma("batched") is True
    assert pallas_gmm.resolve_fma("unbatched") is True
    # per-kernel measurements that DISAGREE are both honored (their
    # grids/VMEM residency legitimately differ)
    pallas_gmm.set_default_fma(False, kernel="unbatched")
    assert pallas_gmm.resolve_fma("batched") is True
    assert pallas_gmm.resolve_fma("unbatched") is False
    # env override beats the measured default
    monkeypatch.setenv("HYPEROPT_TPU_PALLAS_FMA", "0")
    assert pallas_gmm.resolve_fma("batched") is False
    assert pallas_gmm.resolve_fma("unbatched") is False


def test_fma_single_probe_applies_to_both_kernels(monkeypatch):
    """The ROADMAP's pallas_fma_default inconsistency: a probe (or
    set_default_fma call) that measured only ONE kernel must set the
    default for BOTH scorer paths — never measured-FMA on one path and
    silent-MXU on the other."""
    from hyperopt_tpu.ops import pallas_gmm

    monkeypatch.delenv("HYPEROPT_TPU_PALLAS_FMA", raising=False)
    monkeypatch.setattr(pallas_gmm, "_fma_measured_default", None)
    monkeypatch.setattr(pallas_gmm, "_fma_measured_default_unbatched", None)
    pallas_gmm.set_default_fma(True, kernel="batched")
    assert pallas_gmm.resolve_fma("batched") is True
    assert pallas_gmm.resolve_fma("unbatched") is True
    monkeypatch.setattr(pallas_gmm, "_fma_measured_default", None)
    pallas_gmm.set_default_fma(True, kernel="unbatched")
    assert pallas_gmm.resolve_fma("batched") is True
    assert pallas_gmm.resolve_fma("unbatched") is True
    with pytest.raises(ValueError):
        pallas_gmm.resolve_fma("nonesuch")


def test_fma_entry_points_share_the_resolver(monkeypatch):
    """Both public scorer entry points resolve fma=None through
    resolve_fma with their own kernel name — the 'one resolver'
    contract itself."""
    from hyperopt_tpu.ops import pallas_gmm

    seen = []
    real = pallas_gmm.resolve_fma

    def spy(kernel="batched"):
        seen.append(kernel)
        return real(kernel)

    monkeypatch.setattr(pallas_gmm, "resolve_fma", spy)
    below, above = make_pair(K=20, padded_tail=2)
    z = np.random.default_rng(8).uniform(-4, 4, 32).astype(np.float32)
    P = pair_params(*below, *above)
    pallas_gmm.pair_score_pallas(z, P, 20, tc=32, tk=128, interpret=True)
    from hyperopt_tpu.ops.pallas_gmm import pair_score_pallas_batched

    pair_score_pallas_batched(
        np.stack([z, z]), np.stack([np.asarray(P)] * 2), 20,
        tc=32, tk=128, interpret=True,
    )
    assert seen == ["unbatched", "batched"]


def test_fma_probe_not_run_off_tpu(monkeypatch):
    # off-TPU the scorer is xla and the timing probe must never fire
    from hyperopt_tpu.algos import tpe

    monkeypatch.delenv("HYPEROPT_TPU_SCORER", raising=False)
    monkeypatch.setattr(tpe, "_probed_scorer", None)
    called = []
    monkeypatch.setattr(tpe, "_fma_timing_probe", lambda *a, **k: called.append(1))
    assert tpe._use_pallas() == "xla"
    assert not called
