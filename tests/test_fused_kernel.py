"""The fused Pallas suggest mega-kernel (ops/pallas_fused.py): interpret-
mode parity against the unfused reference chain across the broken-space-
adjacent shape grid, trajectory identity at fixed seeds, diag-columns
preservation, tier resolution, and the fused cost-model entry.

The shape grid is single-sourced from scripts/fused_report.py (the
BENCH_TPU_fused artifact generator) so the committed artifact and the
test suite can never check different shapes.
"""

import os
import sys

import numpy as np
import pytest

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(ROOT, "scripts"))

import fused_report  # noqa: E402  (scripts/fused_report.py)


# ---------------------------------------------------------------------
# interpret-mode parity suite (fused vs gmm_sample + pair_score + argmax)
# ---------------------------------------------------------------------


@pytest.mark.parametrize(
    "case", [c for c in fused_report.SHAPE_GRID if c[0] != "tiled_100k"],
    ids=[c[0] for c in fused_report.SHAPE_GRID if c[0] != "tiled_100k"],
)
def test_fused_parity_bitwise_across_shape_grid(case):
    """Default (exact-draw) mode: the fused kernel's winners are
    BITWISE identical to the unfused draw→score→argmax chain — k_below
    edges, single-component mixtures, NEG_BIG padding rows, unbounded
    and log-scale cases."""
    rec = fused_report._parity_case(*case)
    assert rec["winner_bitwise_match"], rec
    assert rec["diag_max_abs_err"] < 1e-3, rec


def test_fused_parity_100k_tiled_shape():
    """The 100k-history bucket (k_above = 2^17 + 1): the component axis
    walks 257 in-kernel tiles and the winner still matches bitwise."""
    case = next(c for c in fused_report.SHAPE_GRID if c[0] == "tiled_100k")
    rec = fused_report._parity_case(*case)
    assert rec["winner_bitwise_match"], rec
    assert rec["k_total"] > 2 ** 17, rec


def test_fused_in_kernel_draw_within_documented_tolerance():
    """The opt-in in-kernel draw (HYPEROPT_TPU_FUSED_DRAW): candidate
    values may differ from gmm_sample's by FMA-contraction ulps — the
    documented tolerance — but no further."""
    case = next(c for c in fused_report.SHAPE_GRID if c[0] == "kb_edge_one_obs")
    rec = fused_report._parity_case(*case, draw_in_kernel=True)
    # winner VALUE within a few ulp of the reference winner (either the
    # same candidate off by contraction rounding, or — at a score
    # near-tie — a neighbouring candidate; neither seen at these seeds
    # beyond ulp scale)
    assert rec["winner_max_abs_err"] < 1e-5, rec
    assert rec["diag_max_abs_err"] < 1e-3, rec


def test_fused_scores_match_pallas_scorer_bitwise():
    """The kernel's scoring stage IS pallas_gmm's online logsumexp: at
    the same (tc, tk) the fused winner equals the batched Pallas
    scorer's argmax bitwise (the score-path identity that makes the
    TPU auto-promotion pallas→fused trajectory-safe)."""
    import jax
    import jax.numpy as jnp

    from hyperopt_tpu.ops import gmm as gmm_ops
    from hyperopt_tpu.ops.pallas_fused import fused_suggest_pallas
    from hyperopt_tpu.ops.pallas_gmm import pair_score_pallas_batched
    from hyperopt_tpu.ops.score import pair_params

    rng = np.random.default_rng(11)
    L, k, n_cand = 2, 2, 100
    C = k * n_cand
    keys = jax.random.split(jax.random.PRNGKey(11), L)
    cands, Ps = [], []
    for li in range(L):
        below = fused_report._mk_mixture(rng, 6, pad=3)
        above = fused_report._mk_mixture(rng, 40, pad=5)
        cand = gmm_ops.gmm_sample(
            keys[li], *below, np.float32(-2.0), np.float32(2.0),
            np.float32(0.0), C, False,
        )
        cands.append(cand)
        Ps.append(pair_params(*below, *above))
    kb = 6 + 1 + 3
    z = jnp.stack(cands)
    P = jnp.stack(Ps)
    s = np.asarray(
        pair_score_pallas_batched(z, P, kb, tc=512, tk=512, interpret=True)
    ).reshape(L, k, n_cand)
    cd = np.asarray(z).reshape(L, k, n_cand)
    idx = np.argmax(s, axis=2)
    ref = np.take_along_axis(cd, idx[:, :, None], axis=2)[:, :, 0]
    win = np.asarray(fused_suggest_pallas(
        z, jnp.zeros_like(z), jnp.zeros((L, 7, kb), jnp.float32), P,
        k_below=kb, k=k, tc=512, tk=512, interpret=True,
    )[0])
    assert np.array_equal(ref.astype(np.float32), win)


def test_fused_argmax_tie_keeps_first_candidate():
    """Duplicate candidates (equal scores) must resolve to the FIRST
    occurrence, cross-tile — jnp.argmax semantics."""
    import jax.numpy as jnp

    from hyperopt_tpu.ops.pallas_fused import fused_suggest_pallas
    from hyperopt_tpu.ops.score import pair_params

    rng = np.random.default_rng(3)
    K = 8
    w = jnp.asarray(np.full(K, 1.0 / K, np.float32))
    mu = jnp.asarray(rng.normal(0, 1, K).astype(np.float32))
    s = jnp.asarray(np.full(K, 1.0, np.float32))
    P = pair_params(w, mu, s, w, mu + 0.5, s)[None]
    # 24 candidates, all identical: every score ties; winner idx must be 0
    cand = jnp.full((1, 24), 0.25, jnp.float32)
    win, idx, *_ = fused_suggest_pallas(
        cand, jnp.zeros_like(cand), jnp.zeros((1, 7, K), jnp.float32), P,
        k_below=K, k=1, tc=8, interpret=True,
    )
    assert int(np.asarray(idx)[0, 0]) == 0
    assert float(np.asarray(win)[0, 0]) == 0.25


# ---------------------------------------------------------------------
# trajectory identity + diag preservation through the suggest plane
# ---------------------------------------------------------------------


def test_fused_trajectory_identical_to_unfused():
    """fmin with HYPEROPT_TPU_SCORER=fused == default fmin, trial for
    trial, at fixed seeds on CPU (the ISSUE-14 acceptance assertion)."""
    rec = fused_report._trajectory_check(n_trials=30, seed=7)
    assert rec["identical"], rec


def test_fused_diag_columns_preserved(monkeypatch):
    """The [L, DIAG_COLS] search-health row still rides the fused
    readback — same shape, same column meaning, values within fp
    tolerance of the unfused path's."""
    from functools import partial

    from hyperopt_tpu import Trials, fmin, hp
    from hyperopt_tpu.algos import tpe, tpe_device
    from hyperopt_tpu.base import Domain

    space = {
        "u": hp.uniform("u", -2.0, 2.0),
        "lu": hp.loguniform("lu", -4.0, 2.0),
        "c": hp.choice("c", [0, 1, 2]),
    }
    trials = Trials()
    fmin(
        lambda c: float(c["u"] ** 2), space,
        algo=partial(tpe.suggest, n_EI_candidates=16), max_evals=25,
        trials=trials, rstate=np.random.default_rng(0),
        show_progressbar=False, verbose=False, max_speculation=0,
    )
    domain = Domain(lambda c: float(c["u"] ** 2), space)

    def one_suggest(scorer, tid, seed):
        if scorer is None:
            monkeypatch.delenv("HYPEROPT_TPU_SCORER", raising=False)
        else:
            monkeypatch.setenv("HYPEROPT_TPU_SCORER", scorer)
        captured = []
        tpe_device._suggest_observers.append(captured.append)
        try:
            tpe.suggest([tid], domain, trials, seed, n_EI_candidates=16)
        finally:
            tpe_device._suggest_observers.remove(captured.append)
        resolve = tpe_device.multi_family_suggest_async(captured[-1])
        resolve()
        return resolve.diag

    diag_ref = one_suggest(None, 1000, 42)
    diag_fused = one_suggest("fused", 1001, 42)
    from hyperopt_tpu.diagnostics import DIAG_COLS

    assert len(diag_fused) == len(diag_ref)
    for df, dr in zip(diag_fused, diag_ref):
        assert df.shape == dr.shape
        assert df.shape[1] == DIAG_COLS
        np.testing.assert_allclose(df, dr, rtol=1e-4, atol=1e-5)


def test_fused_suggest_docs_equal_default_suggest(monkeypatch):
    """One suggest, in-process: the fused tier's trial docs equal the
    default tier's for the same (history, seed)."""
    from functools import partial

    from hyperopt_tpu import Trials, fmin, hp
    from hyperopt_tpu.algos import tpe
    from hyperopt_tpu.base import Domain

    space = {"u": hp.uniform("u", -2.0, 2.0), "n": hp.normal("n", 0.0, 1.0)}
    trials = Trials()
    fmin(
        lambda c: float(c["u"] ** 2 + c["n"] ** 2), space,
        algo=partial(tpe.suggest, n_EI_candidates=24), max_evals=25,
        trials=trials, rstate=np.random.default_rng(1),
        show_progressbar=False, verbose=False, max_speculation=0,
    )
    domain = Domain(lambda c: float(c["u"] ** 2 + c["n"] ** 2), space)
    monkeypatch.delenv("HYPEROPT_TPU_SCORER", raising=False)
    ref = tpe.suggest([900], domain, trials, 5, n_EI_candidates=24)
    monkeypatch.setenv("HYPEROPT_TPU_SCORER", "fused")
    fused = tpe.suggest([901], domain, trials, 5, n_EI_candidates=24)
    for label in space:
        assert list(ref[0]["misc"]["vals"][label]) == list(
            fused[0]["misc"]["vals"][label]
        ), label


# ---------------------------------------------------------------------
# tier resolution + cost model
# ---------------------------------------------------------------------


def test_effective_scorer_fused_tier(monkeypatch):
    from hyperopt_tpu.ops.score import PALLAS_MIN_K, effective_scorer

    monkeypatch.delenv("HYPEROPT_TPU_SCORER", raising=False)
    # auto-selected fused demotes below the VMEM crossover, like pallas
    assert effective_scorer("fused", PALLAS_MIN_K - 1) == "xla"
    assert effective_scorer("fused", PALLAS_MIN_K) == "fused"
    # an explicit force is honored verbatim at any size
    monkeypatch.setenv("HYPEROPT_TPU_SCORER", "fused")
    assert effective_scorer("fused", 8) == "fused"


def test_resolve_fused_env_and_measured(monkeypatch):
    from hyperopt_tpu.ops import pallas_fused

    monkeypatch.delenv("HYPEROPT_TPU_FUSED", raising=False)
    monkeypatch.setattr(pallas_fused, "_fused_measured_default", None)
    assert pallas_fused.resolve_fused() is False  # opt-in: default off
    pallas_fused.set_default_fused(True)
    assert pallas_fused.resolve_fused() is True
    monkeypatch.setenv("HYPEROPT_TPU_FUSED", "0")
    assert pallas_fused.resolve_fused() is False  # env beats measured
    monkeypatch.setenv("HYPEROPT_TPU_FUSED", "1")
    monkeypatch.setattr(pallas_fused, "_fused_measured_default", False)
    assert pallas_fused.resolve_fused() is True


def test_resolve_fused_draw_default_off(monkeypatch):
    from hyperopt_tpu.ops.pallas_fused import resolve_fused_draw

    monkeypatch.delenv("HYPEROPT_TPU_FUSED_DRAW", raising=False)
    assert resolve_fused_draw() is False  # bit-exact default
    monkeypatch.setenv("HYPEROPT_TPU_FUSED_DRAW", "1")
    assert resolve_fused_draw() is True


def test_fused_probe_not_run_off_tpu(monkeypatch):
    from hyperopt_tpu.algos import tpe

    monkeypatch.delenv("HYPEROPT_TPU_SCORER", raising=False)
    monkeypatch.delenv("HYPEROPT_TPU_FUSED", raising=False)
    monkeypatch.setattr(tpe, "_probed_scorer", None)
    monkeypatch.setattr(tpe, "_fused_probe_attempted", False)
    called = []
    monkeypatch.setattr(
        tpe, "_fused_timing_probe", lambda *a, **k: called.append(1)
    )
    assert tpe._use_pallas() == "xla"
    assert not called


def test_pair_score_cost_fused_entry(monkeypatch):
    """The fused cost entry encodes ZERO [C, K] HBM round-trips: its
    traffic is O(C + K) while the XLA entry grows O(C*K), and it drops
    the candidate round trip the plain pallas entry still pays."""
    monkeypatch.setenv("HYPEROPT_TPU_SCORER", "1")  # forces verbatim tiers
    from hyperopt_tpu.ops.score import pair_score_cost

    C, K = 8192, 131_105
    fused = pair_score_cost(C, K, "fused")
    pallas = pair_score_cost(C, K, "pallas")
    xla = pair_score_cost(C, K, "xla")
    # no comp matrix: orders of magnitude below the XLA traffic model
    assert fused["bytes"] < xla["bytes"] / 100
    # no candidate/score round trip either: strictly below pallas
    assert fused["bytes"] < pallas["bytes"]
    # O(C + K) scaling: doubling C adds ~8 bytes/candidate, not O(K)
    fused2 = pair_score_cost(2 * C, K, "fused")
    assert fused2["bytes"] - fused["bytes"] == pytest.approx(4.0 * 2 * C)
    # the matmul subset (MFU's denominator) is scorer-independent
    assert fused["mxu_flops"] == xla["mxu_flops"]
    # the draw/select stages are charged O(C)
    assert fused["flops"] > pallas["flops"]


def test_cont_request_cost_fused_drops_candidate_roundtrip(monkeypatch):
    """profiling's per-family model must not double-charge the fused
    kernel for the candidate round trip (DeviceStats roofline truth)."""
    monkeypatch.setenv("HYPEROPT_TPU_SCORER", "1")
    import jax.numpy as jnp

    from hyperopt_tpu.profiling import _cont_request_cost

    L, cap, capt = 2, 1024, 2048
    args = [None, jnp.zeros((L, cap)), None, None, jnp.zeros(capt)]
    st = dict(cap_b=32, k=1, n_cand=8192, quantized=False, n_buckets=0)
    fused = _cont_request_cost(args, dict(st, scorer="fused"))
    pallas = _cont_request_cost(args, dict(st, scorer="pallas"))
    xla = _cont_request_cost(args, dict(st, scorer="xla"))
    C = 8192
    # the pallas arm charges the 2*L*C*4 candidate round trip on top of
    # its pair_score_cost; the fused arm must not
    assert pallas["bytes"] - fused["bytes"] > 2.0 * L * C * 4.0 * 0.9
    assert fused["bytes"] < xla["bytes"]


def test_fused_statics_key_only_on_fused_programs(monkeypatch):
    """Only fused programs carry the fused_draw static — every other
    tier's signature (and the compile ledger's recorded grid) is
    unchanged by this PR."""
    from functools import partial

    from hyperopt_tpu import Trials, fmin, hp
    from hyperopt_tpu.algos import tpe, tpe_device

    space = {"u": hp.uniform("u", -2.0, 2.0)}

    def capture(scorer):
        if scorer is None:
            monkeypatch.delenv("HYPEROPT_TPU_SCORER", raising=False)
        else:
            monkeypatch.setenv("HYPEROPT_TPU_SCORER", scorer)
        captured = []
        tpe_device._suggest_observers.append(captured.append)
        try:
            fmin(
                lambda c: float(c["u"] ** 2), space,
                algo=partial(tpe.suggest, n_EI_candidates=8), max_evals=24,
                trials=Trials(), rstate=np.random.default_rng(0),
                show_progressbar=False, verbose=False, max_speculation=0,
            )
        finally:
            tpe_device._suggest_observers.remove(captured.append)
        return captured[-1]

    default_req = capture(None)
    fused_req = capture("fused")
    default_st = default_req[0][2]
    fused_st = fused_req[0][2]
    assert "fused_draw" not in default_st
    assert fused_st["fused_draw"] is False
    assert fused_st["scorer"] == "fused"


def test_fused_winners_under_mesh_bitwise_equal_meshless():
    """The PL209 pin contract at RUNTIME: with every pallas_call
    operand pinned replicated, the fused kernel under the virtual
    8-device mesh produces bitwise the meshless winners."""
    import jax
    import jax.numpy as jnp

    from hyperopt_tpu.algos.tpe_device import _fused_winners
    from hyperopt_tpu.analysis.program_lint import virtual_mesh
    from hyperopt_tpu.ops.score import pair_params

    mesh = virtual_mesh()
    if mesh is None:
        pytest.skip("needs >=2 devices (XLA_FLAGS device-count force)")
    rng = np.random.default_rng(0)
    L, kb, ka, k, n_cand = 2, 8, 40, 2, 64

    def mk(n):
        w = rng.uniform(0.1, 1.0, n).astype(np.float32)
        w /= w.sum()
        return (
            jnp.asarray(w),
            jnp.asarray(rng.normal(0, 2, n).astype(np.float32)),
            jnp.asarray(rng.uniform(0.3, 2, n).astype(np.float32)),
        )

    Ps, cands = [], []
    for _ in range(L):
        Ps.append(pair_params(*mk(kb), *mk(ka)))
        cands.append(
            jnp.asarray(rng.normal(0, 1, (k * n_cand,)).astype(np.float32))
        )
    P = jnp.stack(Ps)
    cand = jnp.stack(cands)

    def run(m):
        @jax.jit
        def prog(cand, P):
            win, _ei = _fused_winners(
                m, cand, P, kb, k=k, n_cand=n_cand, log_scale=False,
                fused_draw=False,
            )
            return win

        return np.asarray(prog(cand, P))

    assert np.array_equal(run(None), run(mesh))


# ---------------------------------------------------------------------
# ei_from_partials unit
# ---------------------------------------------------------------------


def test_ei_from_partials_matches_dense_reduction():
    import jax.numpy as jnp

    from hyperopt_tpu.algos.tpe_device import _ei_diag
    from hyperopt_tpu.ops.pallas_fused import ei_from_partials

    rng = np.random.default_rng(0)
    L, k, n_cand, n_top = 3, 4, 37, 16
    scores = rng.normal(0, 3, (L, k, n_cand)).astype(np.float32)
    # per-segment partials computed densely (what the kernel accumulates)
    m = scores.max(axis=2)
    s = np.exp(scores - m[:, :, None]).sum(axis=2)
    top = -np.sort(-scores, axis=2)[:, :, :n_top]
    g_max, g_lme, g_mass = (
        np.asarray(v)
        for v in ei_from_partials(
            jnp.asarray(m), jnp.asarray(s), jnp.asarray(top),
            k * n_cand, n_top,
        )
    )
    r_max, r_lme, r_mass = (
        np.asarray(v)
        for v in _ei_diag(jnp.asarray(scores.reshape(L, k * n_cand)))
    )
    np.testing.assert_allclose(g_max, r_max, rtol=1e-6)
    np.testing.assert_allclose(g_lme, r_lme, rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(g_mass, r_mass, rtol=1e-5, atol=1e-6)
