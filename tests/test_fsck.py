"""fsck for the durable trial store (hyperopt_tpu.resilience.fsck).

Covers the ISSUE 5 store layer: every rule in the FS401-FS408 catalog
detects its damage class in dry-run mode and repairs it in repair mode,
the CRC doc trailer round-trips (legacy docs without one still read),
torn docs quarantine instead of crashing ``all_docs``, and the service
root recursion + CLI entry behave.
"""

import json
import os
import zlib

import pytest

from hyperopt_tpu import hp
from hyperopt_tpu.base import JOB_STATE_DONE, JOB_STATE_NEW
from hyperopt_tpu.parallel.file_trials import (
    DocCorrupt,
    FileTrials,
    _decode_doc,
    _encode_doc,
    _read_doc,
    _write_doc,
)
from hyperopt_tpu.resilience.fsck import fsck_path, fsck_queue, main
from hyperopt_tpu.service import OptimizationService

SPACE = {"x": hp.uniform("x", -5, 5)}


def _mk_queue(tmp_path, n_docs=3, backend="doc"):
    # the FS401-FS408 catalog exercises the per-doc layout; the
    # segmented layout has its own FS410-FS412 catalog below
    qdir = str(tmp_path / "q")
    trials = FileTrials(qdir, backend=backend)
    docs = []
    for tid in trials.new_trial_ids(n_docs):
        doc = {
            "tid": tid, "state": JOB_STATE_NEW, "spec": None,
            "result": {"status": "new"},
            "misc": {
                "tid": tid, "cmd": None, "idxs": {"x": [tid]},
                "vals": {"x": [0.5]}, "workdir": None,
            },
            "exp_key": None, "owner": None, "version": 0,
            "book_time": None, "refresh_time": None,
        }
        trials.insert_trial_docs([doc])
        docs.append(doc)
    return qdir, trials, docs


# ---------------------------------------------------------------------
# the CRC trailer itself
# ---------------------------------------------------------------------


class TestDocTrailer:
    def test_roundtrip(self):
        doc = {"tid": 1, "state": 0, "misc": {"vals": {"x": [1.5]}}}
        raw = _encode_doc(doc)
        assert b"#crc32:" in raw
        assert _decode_doc(raw) == doc

    def test_legacy_doc_without_trailer_reads(self):
        raw = json.dumps({"tid": 2, "state": 1}).encode()
        assert _decode_doc(raw) == {"tid": 2, "state": 1}

    def test_torn_payload_detected(self):
        raw = _encode_doc({"tid": 1, "state": 0})
        with pytest.raises(DocCorrupt):
            _decode_doc(raw[: len(raw) // 2])

    def test_garbled_payload_detected(self):
        raw = bytearray(_encode_doc({"tid": 1, "state": 0}))
        raw[3] ^= 0xFF  # flip one payload byte; trailer now mismatches
        with pytest.raises(DocCorrupt):
            _decode_doc(bytes(raw))

    def test_read_doc_quarantines_and_all_docs_survives(self, tmp_path):
        qdir, trials, docs = _mk_queue(tmp_path)
        victim = trials.jobs.trial_path(docs[1]["tid"])
        with open(victim, "r+b") as f:
            f.truncate(os.path.getsize(victim) // 2)
        got = trials.jobs.all_docs()  # must not raise
        assert [d["tid"] for d in got] == [0, 2]
        assert not os.path.exists(victim)
        assert os.path.exists(victim + ".corrupt")

    def test_crc_matches_payload(self):
        doc = {"tid": 9, "state": 2}
        raw = _encode_doc(doc)
        payload, trailer = raw.rsplit(b"\n#crc32:", 1)
        crc_hex, length = trailer.rstrip(b"\n").split(b":")
        assert int(length) == len(payload)
        assert int(crc_hex, 16) == zlib.crc32(payload) & 0xFFFFFFFF


# ---------------------------------------------------------------------
# rule catalog
# ---------------------------------------------------------------------


class TestRules:
    def test_fs401_torn_doc_quarantined(self, tmp_path):
        qdir, trials, docs = _mk_queue(tmp_path)
        victim = trials.jobs.trial_path(docs[0]["tid"])
        with open(victim, "r+b") as f:
            f.truncate(os.path.getsize(victim) // 2)
        report = fsck_queue(qdir, repair=False)
        assert report.by_rule().get("FS401") == 1
        assert not report.clean
        assert os.path.exists(victim)  # dry run touched nothing
        report = fsck_queue(qdir, repair=True)
        assert report.clean
        assert not os.path.exists(victim)
        assert fsck_queue(qdir, repair=False).clean

    def test_fs402_orphan_lease(self, tmp_path):
        qdir, trials, docs = _mk_queue(tmp_path)
        trials.jobs.grant_lease(docs[0]["tid"], "nobody")  # doc is NEW
        trials.jobs.grant_lease(999, "ghost")  # no doc at all
        report = fsck_queue(qdir, repair=False)
        assert report.by_rule().get("FS402") == 2
        fsck_queue(qdir, repair=True)
        assert trials.jobs.read_lease(docs[0]["tid"]) is None
        assert fsck_queue(qdir, repair=False).clean

    def test_fs403_orphan_lock(self, tmp_path):
        qdir, trials, docs = _mk_queue(tmp_path)
        with open(trials.jobs.lock_path(docs[0]["tid"]), "w") as f:
            f.write("dead-worker")  # doc still NEW: crashed mid-reserve
        with open(trials.jobs.lock_path(777), "w") as f:
            f.write("ghost")
        report = fsck_queue(qdir, repair=False)
        assert report.by_rule().get("FS403") == 2
        fsck_queue(qdir, repair=True)
        assert trials.jobs.locked_tids() == []
        assert fsck_queue(qdir, repair=False).clean

    def test_fs403_running_doc_keeps_lock(self, tmp_path):
        from hyperopt_tpu.base import JOB_STATE_RUNNING

        qdir, trials, docs = _mk_queue(tmp_path)
        doc = dict(docs[0])
        doc["state"] = JOB_STATE_RUNNING
        trials.jobs.write(doc)
        with open(trials.jobs.lock_path(doc["tid"]), "w") as f:
            f.write("live-worker")
        trials.jobs.grant_lease(doc["tid"], "live-worker")
        report = fsck_queue(qdir, repair=True)
        # a RUNNING doc's lock+lease are legitimate — untouched
        assert report.clean and not report.findings
        assert trials.jobs.locked_tids() == [doc["tid"]]

    def test_fs404_tid_filename_mismatch(self, tmp_path):
        qdir, trials, docs = _mk_queue(tmp_path)
        # duplicate doc 0 under the filename of a new tid
        src = trials.jobs.trial_path(docs[0]["tid"])
        dst = trials.jobs.trial_path(42)
        with open(src, "rb") as f:
            raw = f.read()
        with open(dst, "wb") as f:
            f.write(raw)
        report = fsck_queue(qdir, repair=False)
        assert report.by_rule().get("FS404") == 1
        fsck_queue(qdir, repair=True)
        assert not os.path.exists(dst)
        assert os.path.exists(src)
        assert fsck_queue(qdir, repair=False).clean

    def test_fs406_tmp_droppings(self, tmp_path):
        qdir, trials, docs = _mk_queue(tmp_path)
        dropping = os.path.join(
            qdir, "trials", "000000000000.json.tmp.123.456"
        )
        with open(dropping, "w") as f:
            f.write("{partial")
        report = fsck_queue(qdir, repair=False)
        assert report.by_rule().get("FS406") == 1
        fsck_queue(qdir, repair=True)
        assert not os.path.exists(dropping)

    def test_fs408_stuck_counter_lock_and_low_counter(self, tmp_path):
        qdir, trials, docs = _mk_queue(tmp_path)
        with open(os.path.join(qdir, "ids.counter.lock"), "w"):
            pass
        # counter torn back to empty (writer killed mid-write pre-fix)
        with open(os.path.join(qdir, "ids.counter"), "w"):
            pass
        report = fsck_queue(qdir, repair=False)
        assert report.by_rule().get("FS408") == 2
        fsck_queue(qdir, repair=True)
        assert not os.path.exists(os.path.join(qdir, "ids.counter.lock"))
        with open(os.path.join(qdir, "ids.counter")) as f:
            assert int(f.read()) == max(d["tid"] for d in docs) + 1
        assert fsck_queue(qdir, repair=False).clean


# ---------------------------------------------------------------------
# service-level rules: journal restore, seed cursor, torn journal
# ---------------------------------------------------------------------


class TestServiceRules:
    def _service_study(self, tmp_path, n=2):
        root = str(tmp_path / "root")
        svc = OptimizationService(root=root, batch_window=0.001)
        svc.create_study("s", SPACE, seed=5, algo="rand")
        tids = []
        for i in range(n):
            (t,) = svc.suggest("s", idempotency_key=f"k{i}")
            svc.report("s", t["tid"], loss=float(i),
                       idempotency_key=f"r{i}")
            tids.append(t["tid"])
        svc.close()
        return root, os.path.join(root, "studies", "s"), tids

    def test_fs401_restore_from_journal(self, tmp_path, monkeypatch):
        # FS401 journal restore is a per-doc-layout rule: pin the study
        # to the legacy backend (segmented tears are FS410's business)
        monkeypatch.setenv("HYPEROPT_TPU_STORE_BACKEND", "doc")
        root, qdir, tids = self._service_study(tmp_path)
        victim = os.path.join(qdir, "trials", f"{tids[0]:012d}.json")
        with open(victim, "r+b") as f:
            f.truncate(os.path.getsize(victim) // 2)
        report = fsck_path(root, repair=True)
        assert report.by_rule().get("FS401") == 1
        # restored from the journal, report result included
        doc = _read_doc(victim)
        assert doc is not None
        assert doc["state"] == JOB_STATE_DONE
        assert doc["result"]["loss"] == 0.0
        assert fsck_path(root, repair=False).clean

    def test_fs405_stale_seed_cursor(self, tmp_path):
        from hyperopt_tpu.service.core import SEED_CURSOR_ATTACHMENT

        root, qdir, tids = self._service_study(tmp_path)
        cursor = os.path.join(qdir, "attachments", SEED_CURSOR_ATTACHMENT)
        with open(cursor, "w") as f:
            f.write("0")  # rolled back: restart would re-issue seed 1
        report = fsck_path(root, repair=False)
        assert report.by_rule().get("FS405") == 1
        fsck_path(root, repair=True)
        with open(cursor) as f:
            assert int(f.read()) == 2
        assert fsck_path(root, repair=False).clean

    def test_fs407_torn_journal_tail(self, tmp_path):
        from hyperopt_tpu.service.core import ResponseJournal

        root, qdir, tids = self._service_study(tmp_path)
        jpath = os.path.join(
            qdir, "attachments", "ServiceResponseJournal.jsonl"
        )
        size = os.path.getsize(jpath)
        with open(jpath, "r+b") as f:
            f.truncate(size - 7)
        report = fsck_path(root, repair=False)
        assert report.by_rule().get("FS407") == 1
        fsck_path(root, repair=True)
        assert fsck_path(root, repair=False).clean
        # the surviving records still parse
        j = ResponseJournal(path=jpath)
        assert j.n_torn_lines == 0
        assert len(j) == 3  # k0, r0, k1 survive; r1's tail was torn

    def test_clean_root_is_clean(self, tmp_path):
        root, qdir, tids = self._service_study(tmp_path)
        report = fsck_path(root, repair=False)
        assert report.clean and not report.findings
        assert report.n_docs == 2


# ---------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------


class TestCLI:
    def test_dry_run_exit_codes_and_json(self, tmp_path, capsys):
        qdir, trials, docs = _mk_queue(tmp_path)
        assert main([qdir]) == 0
        victim = trials.jobs.trial_path(docs[0]["tid"])
        with open(victim, "r+b") as f:
            f.truncate(3)
        capsys.readouterr()  # drain the first run's text report
        assert main([qdir, "--json"]) == 1
        out = json.loads(capsys.readouterr().out)
        assert out["clean"] is False
        assert out["by_rule"].get("FS401") == 1
        assert main([qdir, "--repair"]) == 0
        assert main([qdir]) == 0

    def test_module_subcommand(self, tmp_path):
        # python -m hyperopt_tpu.service fsck <root> routes here
        from hyperopt_tpu.service.__main__ import main as service_main

        qdir, trials, docs = _mk_queue(tmp_path)
        assert service_main(["fsck", qdir]) == 0


# ---------------------------------------------------------------------
# tmp-dropping GC satellites (requeue_stale + reaper)
# ---------------------------------------------------------------------


class TestTmpDroppingGC:
    def _dropping(self, qdir, sub, name, age=120.0):
        import time as _time

        p = os.path.join(qdir, sub, name) if sub else os.path.join(
            qdir, name
        )
        with open(p, "w") as f:
            f.write("torn")
        old = _time.time() - age
        os.utime(p, (old, old))
        return p

    def test_requeue_stale_gcs_tmp_droppings(self, tmp_path):
        qdir, trials, docs = _mk_queue(tmp_path)
        old = self._dropping(qdir, "trials", "x.json.tmp.1.2")
        old_root = self._dropping(qdir, None, "ids.counter.tmp.1.2")
        fresh = self._dropping(
            qdir, "leases", "y.lease.tmp.3.4", age=0.0
        )
        trials.jobs.requeue_stale(30.0)
        assert not os.path.exists(old)
        assert not os.path.exists(old_root)
        assert os.path.exists(fresh)  # young: may be a write in flight

    def test_reaper_gcs_tmp_droppings(self, tmp_path):
        from hyperopt_tpu.observability import FaultStats
        from hyperopt_tpu.resilience.leases import LeaseReaper

        qdir, trials, docs = _mk_queue(tmp_path)
        old = self._dropping(qdir, "attachments", "blob.tmp.9.9")
        stats = FaultStats()
        reaper = LeaseReaper(trials, stats=stats)
        reaper.reap_once()
        assert not os.path.exists(old)
        assert stats.get("tmp_dropping_cleared") == 1

    def test_requeue_stale_gcs_segment_tmp_droppings(self, tmp_path):
        """The segmented layout's tmp naming (manifest publishes,
        compaction rewrites) is in the GC sweep too — a crash between
        tmp-write and atomic replace must not leak files forever."""
        qdir, trials, docs = _mk_queue(tmp_path, backend="segment")
        old_manifest = self._dropping(
            qdir, "segments", "MANIFEST.json.tmp.11.3"
        )
        old_seg = self._dropping(
            qdir, "segments", "seg-00000001.log.tmp.11.4"
        )
        fresh = self._dropping(
            qdir, "segments", "MANIFEST.json.tmp.12.1", age=0.0
        )
        trials.jobs.requeue_stale(30.0)
        assert not os.path.exists(old_manifest)
        assert not os.path.exists(old_seg)
        assert os.path.exists(fresh)  # young: may be a publish in flight
        # the live store is untouched by the sweep
        assert sorted(d["tid"] for d in trials.jobs.all_docs()) == [
            d["tid"] for d in docs
        ]


# ---------------------------------------------------------------------
# FS409: replica-plane leases / fences / claim locks / registry records
# ---------------------------------------------------------------------

class TestFS409ReplicaPlane:
    def _root_with_study(self, tmp_path, study="st"):
        from hyperopt_tpu.service.replicas import StudyLeaseStore

        root = str(tmp_path / "root")
        os.makedirs(os.path.join(root, "studies", study, "trials"))
        store = StudyLeaseStore(root, ttl=0.2)
        return root, store, study

    def _rules(self, report):
        return [f.rule for f in report.findings]

    def test_orphan_lease_deleted(self, tmp_path):
        import time as _time

        root, store, _ = self._root_with_study(tmp_path)
        store.claim("ghost", "r1")  # no studies/ghost directory
        # while the lease is LIVE this is the mid-create window
        # (ownership-before-side-effects): not damage, not even a
        # finding — deleting it would steal a live creator's lease and
        # reset its fence
        report = fsck_path(root, repair=True)
        assert "FS409" not in self._rules(report)
        assert os.path.exists(store.lease_path("ghost"))
        assert os.path.exists(store.fence_path("ghost"))
        _time.sleep(0.3)  # past the TTL: a crashed creator's residue
        report = fsck_path(root, repair=True)
        assert "FS409" in self._rules(report)
        assert report.clean
        assert not os.path.exists(store.lease_path("ghost"))
        assert not os.path.exists(store.fence_path("ghost"))

    def test_expired_lease_reclaimed_fence_preserved(self, tmp_path):
        import time as _time

        root, store, study = self._root_with_study(tmp_path)
        f1 = store.claim(study, "dead-replica")
        _time.sleep(0.3)  # expired — but within one TTL of grace: a
        # briefly-stalled holder may still renew, so fsck leaves it
        report = fsck_path(root, repair=True)
        assert "FS409" not in self._rules(report)
        assert store.read(study)["owner"] == "dead-replica"
        _time.sleep(0.2)  # past the grace too: dead owner's residue
        report = fsck_path(root, repair=True)
        assert "FS409" in self._rules(report)
        assert report.clean
        lease = store.read(study)
        assert lease["owner"] is None
        assert int(lease["fence"]) == f1  # preserved, not reset
        # the dead owner's credential stays dead; a new claim bumps
        assert not store.verify(study, "dead-replica", f1)
        assert store.claim(study, "r2") == f1 + 1

    def test_torn_lease_quarantined(self, tmp_path):
        root, store, study = self._root_with_study(tmp_path)
        store.claim(study, "r1")
        path = store.lease_path(study)
        with open(path, "r+b") as f:
            f.truncate(os.path.getsize(path) // 2)
        report = fsck_path(root, repair=True)
        assert "FS409" in self._rules(report)
        assert report.clean
        assert not os.path.exists(path)
        assert any(
            n.startswith(os.path.basename(path) + ".corrupt")
            or n == os.path.basename(path) + ".corrupt"
            for n in os.listdir(os.path.dirname(path))
        )

    def test_garbled_fence_counter_rewritten_past_evidence(self, tmp_path):
        root, store, study = self._root_with_study(tmp_path)
        fence = store.claim(study, "r1")
        with open(store.fence_path(study), "w") as f:
            f.write("not-a-number")
        report = fsck_path(root, repair=True)
        assert "FS409" in self._rules(report)
        assert report.clean
        assert store.read_fence(study) == fence + 1

    def test_stuck_claimlock_cleared(self, tmp_path):
        import time as _time

        root, store, study = self._root_with_study(tmp_path)
        lock = store._claim_lock_path(study)
        with open(lock, "w") as f:
            f.write("")
        # a FRESH lock may be a live peer inside the claim critical
        # section (a sibling's startup fsck runs against a live root):
        # untouched, no finding
        report = fsck_path(root, repair=True)
        assert "FS409" not in self._rules(report)
        assert os.path.exists(lock)
        # backdated past the grace: a claimant killed mid-claim
        old = _time.time() - 120.0
        os.utime(lock, (old, old))
        report = fsck_path(root, repair=True)
        assert "FS409" in self._rules(report)
        assert report.clean
        assert not os.path.exists(lock)

    def test_torn_registry_record_deleted(self, tmp_path):
        from hyperopt_tpu.service.replicas import ReplicaDirectory

        root = str(tmp_path / "root")
        directory = ReplicaDirectory(root)
        directory.advertise("r1", "http://127.0.0.1:1")
        path = directory.record_path("r1")
        with open(path, "r+b") as f:
            f.truncate(os.path.getsize(path) // 2)
        report = fsck_path(root, repair=True)
        assert "FS409" in self._rules(report)
        assert report.clean
        assert not os.path.exists(path)

    def test_live_plane_untouched_and_dry_run_reports_only(self, tmp_path):
        import time as _time

        root, store, study = self._root_with_study(tmp_path)
        f1 = store.claim(study, "r1")
        store.claim("ghost", "r2")
        _time.sleep(0.3)  # ghost orphan past TTL; study lease expired
        # but inside the reclaim grace (still safely r1's)
        # dry run: finds the orphan, repairs nothing
        report = fsck_path(root, repair=False)
        assert "FS409" in self._rules(report)
        assert not report.clean
        assert os.path.exists(store.lease_path("ghost"))
        # the LIVE lease is never flagged
        assert not any(
            os.path.basename(f.path).startswith(study + ".")
            for f in report.findings if f.rule == "FS409"
        )
        assert store.verify(study, "r1", f1)


# ---------------------------------------------------------------------
# FS410-FS412: the segmented trial store
# ---------------------------------------------------------------------


class TestFS41xSegmentedStore:
    REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

    def _seg_queue(self, tmp_path, n_docs=3, seal=False):
        qdir, trials, docs = _mk_queue(
            tmp_path, n_docs=n_docs, backend="segment"
        )
        segs = trials.jobs.segments
        if seal:
            segs.seal_active()
        return qdir, trials, docs, segs

    def _replayed_tids(self, qdir):
        ft = FileTrials(qdir)
        ft.refresh()
        return sorted(d["tid"] for d in ft._dynamic_trials)

    def test_fs410_torn_active_tail(self, tmp_path):
        qdir, trials, docs, segs = self._seg_queue(tmp_path)
        seg_dir = os.path.join(qdir, "segments")
        active = json.loads(
            open(os.path.join(seg_dir, "MANIFEST.json"), "rb")
            .read().split(b"\n#crc32:")[0]
        )["active"]
        path = os.path.join(seg_dir, active)
        with open(path, "r+b") as f:
            f.truncate(os.path.getsize(path) - 9)
        report = fsck_queue(qdir, repair=False)
        assert report.by_rule().get("FS410") == 1
        assert not report.clean
        # dry run touched nothing: the torn tail is still on disk
        report = fsck_queue(qdir, repair=True)
        assert report.by_rule().get("FS410") == 1
        assert report.clean
        assert fsck_queue(qdir, repair=False).clean
        # the intact prefix replays; only the torn final record is lost
        assert self._replayed_tids(qdir) == [d["tid"] for d in docs][:-1]

    def test_fs410_corrupt_record_inside_sealed_segment(self, tmp_path):
        qdir, trials, docs, segs = self._seg_queue(tmp_path, seal=True)
        (entry,) = segs.sealed_entries()
        path = os.path.join(qdir, "segments", entry["name"])
        size = os.path.getsize(path)
        with open(path, "r+b") as f:  # garble mid-file, same length
            f.seek(size // 2)
            f.write(b"\xff\xff\xff\xff")
        report = fsck_queue(qdir, repair=False)
        assert "FS410" in report.by_rule()
        report = fsck_queue(qdir, repair=True)
        assert report.clean
        assert fsck_queue(qdir, repair=False).clean
        # surviving records still replay (the garbled one is dropped)
        survivors = self._replayed_tids(qdir)
        assert set(survivors) < set(d["tid"] for d in docs)

    def test_fs411_missing_manifest_rebuilt(self, tmp_path):
        qdir, trials, docs, segs = self._seg_queue(tmp_path, seal=True)
        os.unlink(os.path.join(qdir, "segments", "MANIFEST.json"))
        report = fsck_queue(qdir, repair=False)
        assert "FS411" in report.by_rule()
        assert not os.path.exists(
            os.path.join(qdir, "segments", "MANIFEST.json")
        )  # dry run rebuilt nothing
        report = fsck_queue(qdir, repair=True)
        assert report.clean
        assert fsck_queue(qdir, repair=False).clean
        assert self._replayed_tids(qdir) == [d["tid"] for d in docs]

    def test_fs411_missing_sealed_segment_entry_dropped(self, tmp_path):
        qdir, trials, docs, segs = self._seg_queue(tmp_path, seal=True)
        (entry,) = segs.sealed_entries()
        os.unlink(os.path.join(qdir, "segments", entry["name"]))
        report = fsck_queue(qdir, repair=False)
        assert "FS411" in report.by_rule()
        report = fsck_queue(qdir, repair=True)
        assert report.clean
        assert fsck_queue(qdir, repair=False).clean
        assert self._replayed_tids(qdir) == []  # that data is gone

    def test_fs411_short_sealed_segment_repinned(self, tmp_path):
        qdir, trials, docs, segs = self._seg_queue(tmp_path, seal=True)
        (entry,) = segs.sealed_entries()
        path = os.path.join(qdir, "segments", entry["name"])
        with open(path, "r+b") as f:
            f.truncate(os.path.getsize(path) - 9)
        report = fsck_queue(qdir, repair=False)
        assert "FS411" in report.by_rule()
        report = fsck_queue(qdir, repair=True)
        assert report.clean
        assert fsck_queue(qdir, repair=False).clean
        assert self._replayed_tids(qdir) == [d["tid"] for d in docs][:-1]

    def test_fs412_orphan_segment_deleted(self, tmp_path):
        qdir, trials, docs, segs = self._seg_queue(tmp_path)
        orphan = os.path.join(qdir, "segments", "seg-00000042.log")
        with open(orphan, "wb") as f:
            f.write(b"\nretired data the dead compactor left behind")
        report = fsck_queue(qdir, repair=False)
        assert report.by_rule().get("FS412") == 1
        assert os.path.exists(orphan)  # dry run deleted nothing
        report = fsck_queue(qdir, repair=True)
        assert report.clean
        assert not os.path.exists(orphan)
        assert fsck_queue(qdir, repair=False).clean
        assert self._replayed_tids(qdir) == [d["tid"] for d in docs]

    def test_fs412_orphan_with_acked_records_rehomed(self, tmp_path):
        """The compaction chaos window can strand ACKED records in the
        orphaned old active: an appender whose post-append manifest
        check ran before the swap left fsync'd records there, and the
        compactor died before re-homing them.  The repair must replay
        them into the active segment, never silently delete them."""
        from hyperopt_tpu import journal_io
        from hyperopt_tpu.parallel.file_trials import _json_default

        qdir, trials, docs, segs = self._seg_queue(tmp_path)
        straggler = {
            "tid": 77, "state": JOB_STATE_NEW, "misc": {"tid": 77},
        }
        orphan = os.path.join(qdir, "segments", "seg-00000042.log")
        journal_io.append_records(
            orphan, [straggler], default=_json_default,
            fsync_kind="segment",
        )
        report = fsck_queue(qdir, repair=False)
        assert report.by_rule().get("FS412") == 1
        assert os.path.exists(orphan)  # dry run touched nothing
        report = fsck_queue(qdir, repair=True)
        assert report.clean
        assert not os.path.exists(orphan)
        assert fsck_queue(qdir, repair=False).clean
        # the acked record survived the sweep
        assert self._replayed_tids(qdir) == (
            [d["tid"] for d in docs] + [77]
        )

    def test_fs412_stale_orphan_copy_not_rehomed(self, tmp_path):
        """An orphan can also hold a SUPERSEDED copy of a doc (the
        pre-compaction history).  Re-homing it would regress the trial
        state under latest-wins replay — only records the replayed view
        does not supersede move."""
        from hyperopt_tpu import journal_io
        from hyperopt_tpu.parallel.file_trials import _json_default

        qdir, trials, docs, segs = self._seg_queue(tmp_path)
        done = dict(docs[0])
        done["state"] = JOB_STATE_DONE
        segs.append(done)
        orphan = os.path.join(qdir, "segments", "seg-00000042.log")
        journal_io.append_records(
            orphan, [docs[0]], default=_json_default,
            fsync_kind="segment",
        )  # the stale NEW-state copy
        report = fsck_queue(qdir, repair=True)
        assert report.clean
        assert not os.path.exists(orphan)
        ft = FileTrials(qdir)
        ft.refresh()
        states = {d["tid"]: d["state"] for d in ft._dynamic_trials}
        assert states[docs[0]["tid"]] == JOB_STATE_DONE

    def test_sigkill_mid_segment_append_recovers(self, tmp_path):
        """A REAL process SIGKILLed inside a segment group commit (the
        chaos torn-segment site: tail clipped, then the process dies
        before acking).  fsck finds the torn tail (FS410), the repair
        keeps the committed prefix, and the unacked batch is simply
        absent — never half-applied."""
        import subprocess
        import sys

        qdir, trials, docs, segs = self._seg_queue(tmp_path, n_docs=2)
        code = f"""
import sys
sys.path.insert(0, {self.REPO!r})
from hyperopt_tpu.resilience import chaos
from hyperopt_tpu.parallel.file_trials import FileJobs
cfg = chaos.ChaosConfig(seed=5, p_torn_segment=1.0)
with chaos.active(chaos.ChaosMonkey(cfg)):
    jobs = FileJobs({qdir!r})
    jobs.insert({{"tid": 99, "state": 0, "misc": {{"tid": 99}}}})
raise SystemExit("chaos torn-segment site never fired")
"""
        proc = subprocess.run(
            [sys.executable, "-c", code],
            capture_output=True, text=True, timeout=60,
            env={**os.environ, "JAX_PLATFORMS": "cpu"},
        )
        assert proc.returncode == -9, (proc.returncode, proc.stderr)
        report = fsck_queue(qdir, repair=True)
        assert "FS410" in report.by_rule()
        assert report.clean
        assert fsck_queue(qdir, repair=False).clean
        # the unacked tid-99 append vanished whole; the acked docs stand
        assert self._replayed_tids(qdir) == [d["tid"] for d in docs]

    def test_sigkill_mid_compaction_leaves_only_orphans(self, tmp_path):
        """A compactor SIGKILLed between publishing the compacted
        manifest and unlinking the retired segments (the chaos
        compaction-kill window).  The store is already correct — the
        new lineage is live — and fsck just sweeps the orphans
        (FS412)."""
        import subprocess
        import sys

        qdir, trials, docs, segs = self._seg_queue(tmp_path, seal=True)
        code = f"""
import sys
sys.path.insert(0, {self.REPO!r})
from hyperopt_tpu.resilience import chaos
from hyperopt_tpu.parallel.file_trials import FileJobs
cfg = chaos.ChaosConfig(seed=5, p_compaction_kill=1.0)
with chaos.active(chaos.ChaosMonkey(cfg)):
    jobs = FileJobs({qdir!r})
    jobs.segments.compact()
raise SystemExit("chaos compaction-kill site never fired")
"""
        proc = subprocess.run(
            [sys.executable, "-c", code],
            capture_output=True, text=True, timeout=60,
            env={**os.environ, "JAX_PLATFORMS": "cpu"},
        )
        assert proc.returncode == -9, (proc.returncode, proc.stderr)
        # every doc is intact BEFORE any repair: the compacted lineage
        # was published atomically
        assert self._replayed_tids(qdir) == [d["tid"] for d in docs]
        report = fsck_queue(qdir, repair=True)
        assert "FS412" in report.by_rule()
        assert report.clean
        assert fsck_queue(qdir, repair=False).clean
        assert self._replayed_tids(qdir) == [d["tid"] for d in docs]
