// fastqueue: native fast path for the FileTrials durable queue.
//
// The FileTrials driver/worker poll loop is O(N_trials) per poll: list the
// trials directory, read each small JSON doc, extract its job state.  In
// Python that is an open+json.loads per file per poll; at 10k-trial queues
// polled multiple times a second this dominates the control plane.  This
// translation unit provides the three hot operations as plain C symbols
// (loaded via ctypes, no pybind11 needed):
//
//   fq_count_states  - one pass over the trials dir, counting docs per
//                      JOB_STATE (the driver's count_by_state poll)
//   fq_list_new      - tids of docs currently in JOB_STATE_NEW, sorted
//                      (the worker's reservation scan)
//   fq_try_lock      - O_CREAT|O_EXCL lock-file creation stamping the
//                      owner (THE atomic reservation primitive; identical
//                      semantics to the Python implementation)
//
// Doc writes stay in Python: the lock holder rewrites the JSON doc, so the
// native layer never has to serialize documents.  State extraction scans
// for the `"state":` key textually — safe because FileJobs is the only
// writer and always emits `json.dumps(..., sort_keys=True)` docs.  Any
// parse miss is reported as state -1 and the Python caller falls back to
// its exact parser.

#include <algorithm>
#include <cerrno>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <dirent.h>
#include <fcntl.h>
#include <sys/stat.h>
#include <unistd.h>
#include <vector>

namespace {

// Read a whole (small) file into buf; returns length or -1.
long read_file(const char *path, std::vector<char> &buf) {
  int fd = open(path, O_RDONLY);
  if (fd < 0)
    return -1;
  struct stat st;
  if (fstat(fd, &st) != 0) {
    close(fd);
    return -1;
  }
  buf.resize(static_cast<size_t>(st.st_size) + 1);
  long off = 0;
  while (off < st.st_size) {
    ssize_t r = read(fd, buf.data() + off, st.st_size - off);
    if (r <= 0) {
      close(fd);
      return -1;
    }
    off += r;
  }
  close(fd);
  buf[off] = '\0';
  return off;
}

// Extract the integer after a top-level "state": key.  Returns -1 when the
// pattern is absent/malformed (caller falls back to exact JSON parsing).
int extract_state(const char *data) {
  const char *p = strstr(data, "\"state\":");
  if (!p)
    return -1;
  p += 8;
  while (*p == ' ' || *p == '\t')
    ++p;
  if (*p < '0' || *p > '9')
    return -1;
  return atoi(p);
}

// Trial docs are named <tid padded to 12>.json; returns tid or -1.
long parse_tid(const char *name) {
  size_t len = strlen(name);
  if (len < 6 || strcmp(name + len - 5, ".json") != 0)
    return -1;
  for (size_t i = 0; i < len - 5; ++i)
    if (name[i] < '0' || name[i] > '9')
      return -1;
  return atol(name);
}

} // namespace

extern "C" {

// Count docs per state. counts must have room for n_states entries; docs
// whose state is unparseable or >= n_states land in counts[n_states-1]
// ... actually they are reported via the return value's sign: we return
// the number of docs scanned, or -1 on directory errors, and increment
// *unparsed for fallback detection.
int fq_count_states(const char *trials_dir, long *counts, int n_states,
                    long *unparsed) {
  DIR *d = opendir(trials_dir);
  if (!d)
    return -1;
  for (int i = 0; i < n_states; ++i)
    counts[i] = 0;
  *unparsed = 0;
  int n_docs = 0;
  std::vector<char> buf;
  char path[4096];
  struct dirent *e;
  while ((e = readdir(d)) != nullptr) {
    if (parse_tid(e->d_name) < 0)
      continue;
    snprintf(path, sizeof(path), "%s/%s", trials_dir, e->d_name);
    if (read_file(path, buf) < 0) {
      ++*unparsed;
      continue;
    }
    int st = extract_state(buf.data());
    if (st < 0 || st >= n_states) {
      ++*unparsed;
      continue;
    }
    ++counts[st];
    ++n_docs;
  }
  closedir(d);
  return n_docs;
}

// Collect sorted tids of docs in `want_state`.  Returns count written (at
// most max_out) or -1 on directory errors.
int fq_list_state(const char *trials_dir, int want_state, long *tids,
                  int max_out) {
  DIR *d = opendir(trials_dir);
  if (!d)
    return -1;
  std::vector<long> found;
  std::vector<char> buf;
  char path[4096];
  struct dirent *e;
  while ((e = readdir(d)) != nullptr) {
    long tid = parse_tid(e->d_name);
    if (tid < 0)
      continue;
    snprintf(path, sizeof(path), "%s/%s", trials_dir, e->d_name);
    if (read_file(path, buf) < 0)
      continue;
    if (extract_state(buf.data()) == want_state)
      found.push_back(tid);
  }
  closedir(d);
  std::sort(found.begin(), found.end());
  int n = static_cast<int>(found.size());
  if (n > max_out)
    n = max_out;
  for (int i = 0; i < n; ++i)
    tids[i] = found[i];
  return n;
}

// Atomic reservation: exclusive-create the lock file and stamp the owner.
// Returns 1 on success, 0 if already locked, -1 on other errors.
int fq_try_lock(const char *lock_path, const char *owner) {
  int fd = open(lock_path, O_CREAT | O_EXCL | O_WRONLY, 0644);
  if (fd < 0)
    return errno == EEXIST ? 0 : -1;
  size_t len = strlen(owner);
  ssize_t w = write(fd, owner, len);
  close(fd);
  return (w == static_cast<ssize_t>(len)) ? 1 : -1;
}

} // extern "C"
