"""BASELINE config 3: TPE on a mixed/conditional space (SVM-style).

A ``hp.choice`` over kernel families where each branch has its own
hyperparameters — the conditional-space shape that exercises the
vectorizer's branch-activity masks and TPE's per-branch posteriors.
The objective is a synthetic stand-in for SVM cross-validation loss
(no sklearn dependency needed to demo the space mechanics).
"""

from functools import partial

import numpy as np

from hyperopt_tpu import Trials, fmin, hp, space_eval, tpe

space = hp.choice(
    "kernel",
    [
        {
            "type": "linear",
            "C": hp.loguniform("C_lin", np.log(1e-3), np.log(1e3)),
        },
        {
            "type": "rbf",
            "C": hp.loguniform("C_rbf", np.log(1e-3), np.log(1e3)),
            "gamma": hp.loguniform("gamma", np.log(1e-4), np.log(1e1)),
        },
        {
            "type": "poly",
            "C": hp.loguniform("C_poly", np.log(1e-3), np.log(1e3)),
            "degree": hp.quniform("degree", 2, 5, 1),
        },
    ],
)


def objective(cfg):
    # synthetic CV-loss surface: rbf with C≈10, gamma≈0.1 is optimal
    c_pen = (np.log10(cfg["C"]) - 1.0) ** 2
    if cfg["type"] == "rbf":
        return 0.05 + 0.1 * c_pen + (np.log10(cfg["gamma"]) + 1.0) ** 2
    if cfg["type"] == "poly":
        return 0.30 + 0.1 * c_pen + 0.05 * (cfg["degree"] - 3) ** 2
    return 0.25 + 0.1 * c_pen


def main():
    trials = Trials()
    best = fmin(
        fn=objective,
        space=space,
        algo=partial(tpe.suggest, n_EI_candidates=256),  # partial-as-config
        max_evals=200,
        trials=trials,
        rstate=np.random.default_rng(7),
        show_progressbar=False,
        # warm-start from a known-decent point (reference: points_to_evaluate)
        points_to_evaluate=[{"kernel": 1, "C_rbf": 10.0, "gamma": 0.1}],
    )
    cfg = space_eval(space, best)
    print("best config:", cfg)
    print(f"best loss: {min(trials.losses()):.4f}")
    assert cfg["type"] == "rbf", "TPE should discover the rbf branch"


if __name__ == "__main__":
    main()
