"""BASELINE config 1: TPE on Branin-2D, 100 trials.

The Branin function has three global minima with value ~0.398; TPE should
get within ~0.5 of it in 100 trials. Reference equivalent:
``fmin(branin, space, algo=tpe.suggest, max_evals=100)``
(``hyperopt/tests/test_domains.py — branin``).
"""

import math

import numpy as np

from hyperopt_tpu import Trials, fmin, hp, space_eval, tpe


def branin(params):
    x, y = params["x"], params["y"]
    a, b, c = 1.0, 5.1 / (4 * math.pi**2), 5.0 / math.pi
    r, s, t = 6.0, 10.0, 1.0 / (8 * math.pi)
    return a * (y - b * x**2 + c * x - r) ** 2 + s * (1 - t) * math.cos(x) + s


space = {
    "x": hp.uniform("x", -5.0, 10.0),
    "y": hp.uniform("y", 0.0, 15.0),
}


def main():
    trials = Trials()
    best = fmin(
        fn=branin,
        space=space,
        algo=tpe.suggest,
        max_evals=100,
        trials=trials,
        rstate=np.random.default_rng(123),  # seeded → exactly reproducible
        show_progressbar=True,
    )
    print("argmin:", best)
    print("best config:", space_eval(space, best))
    print(f"best loss: {min(trials.losses()):.4f}  (global optimum ~0.398)")


if __name__ == "__main__":
    main()
