"""BASELINE config 5: JaxTrials batched-parallel trials + mesh-sharded TPE.

The SparkTrials analog: ``JaxTrials(parallelism=k)`` evaluates up to k
trials concurrently (host thread plane), and jittable objectives can be
vector-evaluated on device in one batched call (``device_fn``). On a
multi-chip slice, ``tpe.suggest(mesh=…)`` additionally shards candidate
scoring across devices (candidates over ``dp``, mixture components over
``sp`` — the long-history scaling path).

This script adapts to whatever devices exist: 1 CPU, 1 TPU chip, or a
pod slice (run under ``XLA_FLAGS=--xla_force_host_platform_device_count=8
JAX_PLATFORMS=cpu`` to see the sharded path without TPUs).
"""

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from hyperopt_tpu import JaxTrials, fmin, hp, tpe
from hyperopt_tpu.parallel.sharding import default_mesh

space = {
    "x": hp.uniform("x", -5.0, 10.0),
    "y": hp.uniform("y", 0.0, 15.0),
}


def branin_host(cfg):
    import math

    x, y = cfg["x"], cfg["y"]
    a, b, c = 1.0, 5.1 / (4 * math.pi**2), 5.0 / math.pi
    r, s, t = 6.0, 10.0, 1.0 / (8 * math.pi)
    return a * (y - b * x**2 + c * x - r) ** 2 + s * (1 - t) * math.cos(x) + s


def branin_device(cfg):
    # same function, jnp ops: JaxTrials vector-evaluates a whole batch of
    # configs in one jitted device call
    x, y = cfg["x"], cfg["y"]
    a, b, c = 1.0, 5.1 / (4 * jnp.pi**2), 5.0 / jnp.pi
    r, s, t = 6.0, 10.0, 1.0 / (8 * jnp.pi)
    return a * (y - b * x**2 + c * x - r) ** 2 + s * (1 - t) * jnp.cos(x) + s


def main():
    n_dev = len(jax.devices())
    mesh = default_mesh() if n_dev > 1 else None
    print(f"{n_dev} device(s); mesh = {mesh and dict(zip(mesh.axis_names, mesh.devices.shape))}")

    trials = JaxTrials(parallelism=8, device_fn=branin_device, mesh=mesh)
    algo = partial(tpe.suggest, n_EI_candidates=4096, mesh=mesh)
    fmin(
        fn=branin_host,  # fallback when the device plane is unavailable
        space=space,
        algo=algo,
        max_evals=64,
        trials=trials,
        rstate=np.random.default_rng(5),
        show_progressbar=True,
        return_argmin=False,
    )
    print(f"best loss over {len(trials)} parallel trials: "
          f"{min(trials.losses()):.4f} (optimum ~0.398)")


if __name__ == "__main__":
    main()
