"""BASELINE config 2: random search and annealing on Hartmann-6D.

Both algorithms ride the same one-time-compiled space sampler
(``hyperopt_tpu.vectorize.CompiledSpace``): rand draws whole batches in a
single jitted device call; anneal reuses the sampler with per-node
parameters shrunk around the incumbent. Global minimum: -3.32237.
"""

import numpy as np

from hyperopt_tpu import Trials, anneal, fmin, hp, rand

A = np.array(
    [
        [10, 3, 17, 3.5, 1.7, 8],
        [0.05, 10, 17, 0.1, 8, 14],
        [3, 3.5, 1.7, 10, 17, 8],
        [17, 8, 0.05, 10, 0.1, 14],
    ]
)
P = 1e-4 * np.array(
    [
        [1312, 1696, 5569, 124, 8283, 5886],
        [2329, 4135, 8307, 3736, 1004, 9991],
        [2348, 1451, 3522, 2883, 3047, 6650],
        [4047, 8828, 8732, 5743, 1091, 381],
    ]
)
ALPHA = np.array([1.0, 1.2, 3.0, 3.2])


def hartmann6(params):
    x = np.array([params[f"x{i}"] for i in range(6)])
    inner = np.sum(A * (x[None, :] - P) ** 2, axis=1)
    return float(-np.dot(ALPHA, np.exp(-inner)))


space = {f"x{i}": hp.uniform(f"x{i}", 0.0, 1.0) for i in range(6)}


def run(algo, name, seed=42, n=150):
    trials = Trials()
    fmin(
        fn=hartmann6,
        space=space,
        algo=algo,
        max_evals=n,
        trials=trials,
        rstate=np.random.default_rng(seed),
        show_progressbar=False,
    )
    print(f"{name:>8}: best loss after {n} trials = {min(trials.losses()):.4f}")
    return min(trials.losses())


def main():
    print("Hartmann-6D (global minimum -3.32237)")
    b_rand = run(rand.suggest, "rand")
    b_anneal = run(anneal.suggest, "anneal")
    assert b_anneal <= b_rand + 0.5, "annealing should be competitive with random"


if __name__ == "__main__":
    main()
