"""BASELINE config 4: ATPE on a large trial history.

ATPE featurizes the space and the accumulated history, runs the shipped
meta-model artifacts (``hyperopt_tpu/models/atpe_models/``) to choose
TPE meta-parameters (gamma, n_EI_candidates, prior_weight, …), picks
parameter locks from per-parameter loss correlations, and selects a
trial filter (the resultFilteringMode analog) — then delegates to
``tpe.suggest``. The objective is an XGBoost-surrogate-style additive
surface over a realistic mixed space.
"""

import numpy as np

from hyperopt_tpu import Trials, atpe, fmin, hp

space = {
    "learning_rate": hp.loguniform("learning_rate", np.log(1e-4), np.log(1.0)),
    "max_depth": hp.quniform("max_depth", 2, 12, 1),
    "subsample": hp.uniform("subsample", 0.5, 1.0),
    "reg_lambda": hp.loguniform("reg_lambda", np.log(1e-3), np.log(10.0)),
    "booster": hp.choice("booster", ["gbtree", "dart"]),
}


def surrogate(cfg):
    # smooth surrogate of an HPOBench-tabular XGBoost loss surface
    lr = np.log10(cfg["learning_rate"])
    loss = (
        0.10
        + 0.04 * (lr + 1.5) ** 2
        + 0.002 * (cfg["max_depth"] - 6) ** 2
        + 0.05 * (cfg["subsample"] - 0.85) ** 2
        + 0.01 * (np.log10(cfg["reg_lambda"]) - 0.0) ** 2
        + (0.005 if cfg["booster"] == "dart" else 0.0)
    )
    return float(loss)


def main():
    trials = Trials()
    fmin(
        fn=surrogate,
        space=space,
        algo=atpe.suggest,
        max_evals=300,  # long history: the regime ATPE's meta layer targets
        trials=trials,
        rstate=np.random.default_rng(99),
        show_progressbar=True,
        return_argmin=False,
    )
    best = trials.best_trial
    print("best loss:", best["result"]["loss"])
    print("best vals:", {k: v[0] for k, v in best["misc"]["vals"].items() if v})
    assert best["result"]["loss"] < 0.14


if __name__ == "__main__":
    main()
