"""Headline benchmark: TPE suggestions/sec at a 10k-trial history.

BASELINE.md metric: "TPE suggestions/sec @ 10k-trial history" with the
north-star of ≥1000× the CPU reference's candidate-EI evaluations/sec.
The reference (gsmafra/hyperopt) is pure numpy on CPU and is not installed
in this image, so ``vs_baseline`` is measured against a faithful numpy
REIMPLEMENTATION of the same per-suggest computation (adaptive-Parzen fit
of l/g per label + O(candidates × history) log-density scoring) — the
exact math this framework runs as fused XLA kernels, at the same
n_EI_candidates.  (Label it accordingly: this is *not* the reference's own
code path, which is unobtainable offline.)

The timed loop grows the history by one completed trial per suggest, so it
exercises the production steady state: the device-resident history
(``tpe_device.DeviceHistory``) absorbs each append incrementally and
``host_transfer_ms`` reports the measured host→device traffic per suggest
— the evidence that nothing re-uploads the 10k-trial history.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N, ...extras}

Runs on the default JAX platform (the real TPU chip under axon; CPU
elsewhere).  Do not run under tests/conftest.py (that forces CPU).
"""

import json
import os
import sys
import time

import numpy as np

# Headline config (BASELINE.md); env knobs exist for quick smoke runs on
# CPU (e.g. BENCH_N_HISTORY=1000 BENCH_N_CAND=256 BENCH_TIMED=5).
N_HISTORY = int(os.environ.get("BENCH_N_HISTORY", 10_000))
N_LABELS = 5
N_EI_CANDIDATES = int(os.environ.get("BENCH_N_CAND", 8_192))
GAMMA = 0.25
LF = 25
TIMED_SUGGESTS = int(os.environ.get("BENCH_TIMED", 30))

# v5e peak: 197 TFLOP/s bf16 MXU (f32 runs at a fraction of this; MFU is
# reported against the bf16 peak, i.e. conservatively low)
TPU_PEAK_TFLOPS = 197.0


def build_history_trials():
    """10k completed trials over a 5-label mixed space (doc-building cost
    excluded from timing)."""
    from hyperopt_tpu import Trials, hp
    from hyperopt_tpu.base import JOB_STATE_DONE, STATUS_OK, Domain

    space = {
        "lr": hp.loguniform("lr", np.log(1e-5), np.log(1.0)),
        "momentum": hp.uniform("momentum", 0.0, 1.0),
        "width": hp.quniform("width", 32, 1024, 32),
        "sigma": hp.lognormal("sigma", 0.0, 1.0),
        "z": hp.normal("z", 0.0, 3.0),
    }
    domain = Domain(lambda c: 0.0, space)
    rng = np.random.default_rng(0)
    vals, _ = domain.space.sample_batch(0, N_HISTORY)
    losses = rng.standard_normal(N_HISTORY)
    docs = []
    for i in range(N_HISTORY):
        docs.append(_done_doc(i, {k: float(vals[k][i]) for k in vals}, float(losses[i])))
    trials = Trials()
    trials._insert_trial_docs(docs)
    trials.refresh()
    return domain, trials


def _done_doc(tid, config, loss):
    from hyperopt_tpu.base import JOB_STATE_DONE, STATUS_OK

    return {
        "tid": tid,
        "spec": None,
        "result": {"status": STATUS_OK, "loss": loss},
        "misc": {
            "tid": tid,
            "cmd": None,
            "idxs": {k: [tid] for k in config},
            "vals": {k: [v] for k, v in config.items()},
        },
        "state": JOB_STATE_DONE,
        "owner": None,
        "book_time": None,
        "refresh_time": None,
        "exp_key": None,
    }


# ---------------------------------------------------------------------
# numpy reference implementation (CPU-hyperopt-equivalent compute)
# ---------------------------------------------------------------------


def _np_parzen(obs, prior_mu, prior_sigma, lf=LF):
    n = len(obs)
    if n == 0:
        return np.array([1.0]), np.array([prior_mu]), np.array([prior_sigma])
    order = np.argsort(obs)
    srtd = obs[order]
    pos = int(np.searchsorted(srtd, prior_mu))
    mus = np.insert(srtd, pos, prior_mu)
    k = n + 1
    sigma = np.zeros(k)
    if k > 1:
        gaps_l = np.diff(mus, prepend=mus[0])
        gaps_r = np.diff(mus, append=mus[-1])
        sigma = np.maximum(gaps_l, gaps_r)
        sigma[0] = mus[1] - mus[0]
        sigma[-1] = mus[-1] - mus[-2]
    sigma = np.clip(sigma, prior_sigma / min(100.0, 1.0 + k), prior_sigma)
    sigma[pos] = prior_sigma
    if lf and n > lf:
        w = np.concatenate([np.linspace(1.0 / n, 1.0, n - lf), np.ones(lf)])
    else:
        w = np.ones(n)
    w = w[order]
    weights = np.insert(w, pos, 1.0)
    weights /= weights.sum()
    return weights, mus, sigma


def _np_gmm_lpdf(x, w, mu, sigma):
    # O(C x K) — the reference's hot loop
    mahal = ((x[:, None] - mu[None, :]) / sigma[None, :]) ** 2
    comp = -0.5 * mahal - np.log(sigma * np.sqrt(2 * np.pi))[None, :] + np.log(w)[None, :]
    m = comp.max(axis=1, keepdims=True)
    return (m[:, 0]) + np.log(np.exp(comp - m).sum(axis=1))


def numpy_reference_suggest(hist, rng, n_cand=N_EI_CANDIDATES):
    losses = hist.losses
    n = len(losses)
    n_below = min(int(np.ceil(GAMMA * np.sqrt(n))), LF)
    order = np.argsort(losses, kind="stable")
    below_tids = hist.loss_tids[order[:n_below]]
    out = {}
    for label, tids in hist.idxs.items():
        obs = np.asarray(hist.vals[label], dtype=np.float64)
        mask = np.isin(tids, below_tids)
        b, a = obs[mask], obs[~mask]
        wb, mb, sb = _np_parzen(b, float(obs.mean()), float(obs.std() + 1e-3))
        wa, ma, sa = _np_parzen(a, float(obs.mean()), float(obs.std() + 1e-3))
        comp = rng.choice(len(wb), size=n_cand, p=wb)
        cand = rng.normal(mb[comp], sb[comp])
        score = _np_gmm_lpdf(cand, wb, mb, sb) - _np_gmm_lpdf(cand, wa, ma, sa)
        out[label] = cand[np.argmax(score)]
    return out


def _ensure_live_backend():
    """Guard against a wedged TPU tunnel: probe device init in a throwaway
    subprocess; on hang/failure re-exec this bench on CPU.  (Setting
    JAX_PLATFORMS alone is not enough — the axon sitecustomize overrides
    the config in every process — so the axon env trigger is removed.)"""
    import subprocess

    if os.environ.get("BENCH_BACKEND_PROBED"):
        return
    try:
        subprocess.run(
            [sys.executable, "-c", "import jax; jax.devices()"],
            timeout=180,
            capture_output=True,
            check=True,
        )
        os.environ["BENCH_BACKEND_PROBED"] = "1"
        return
    except (subprocess.TimeoutExpired, subprocess.CalledProcessError):
        pass
    print("bench: TPU backend unreachable, falling back to CPU", file=sys.stderr)
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)  # disables axon registration
    env["JAX_PLATFORMS"] = "cpu"
    env["BENCH_BACKEND_PROBED"] = "1"
    os.execve(sys.executable, [sys.executable] + sys.argv, env)


def _scorer_flops(dh, n_cand):
    """MXU matmul FLOPs per suggest in the pair scorer: F[C,3] @ P[3,K]
    per continuous family label (2·3·C·K), K = both padded mixtures."""
    flops = 0
    for fam in dh.families.values():
        if fam.key[0] != "cont":
            continue
        cap_b = 32  # bucket(n_below) at 10k history (n_below = 25)
        K = (cap_b + 1) + (fam.cap + 1)
        flops += fam.L * 2 * 3 * n_cand * K
    return flops


def _pallas_ab(platform):
    """Pallas-vs-XLA scorer A/B on real TPU hardware (VERDICT r1 #2)."""
    if platform != "tpu" or os.environ.get("BENCH_AB") == "0":
        return None
    import jax
    import jax.numpy as jnp

    from hyperopt_tpu.ops import parzen as parzen_ops
    from hyperopt_tpu.ops.pallas_gmm import pair_score_pallas
    from hyperopt_tpu.ops.score import pair_params, pair_score

    out = {}
    rng = np.random.default_rng(0)
    for n_hist in (1_000, 10_000):
        cap = parzen_ops.bucket(n_hist)
        obs = jnp.asarray(rng.normal(size=cap).astype(np.float32))
        wa, ma, sa = parzen_ops.adaptive_parzen_normal_padded(
            obs, n_hist, jnp.float32(1.0), jnp.float32(0.0), jnp.float32(10.0), LF
        )
        wb, mb, sb = parzen_ops.adaptive_parzen_normal_padded(
            obs[:32], 25, jnp.float32(1.0), jnp.float32(0.0), jnp.float32(10.0), LF
        )
        params = pair_params(wb, mb, sb, wa, ma, sa)
        k_below = int(wb.shape[0])
        for n_cand in (8_192, 65_536):
            z = jnp.asarray(rng.normal(size=n_cand).astype(np.float32))
            for name, fn in (
                ("xla", lambda: pair_score(z, params, k_below=k_below)),
                ("pallas", lambda: pair_score_pallas(z, params, k_below=k_below)),
            ):
                r = fn()
                jax.block_until_ready(r)
                t0 = time.perf_counter()
                reps = 20
                for _ in range(reps):
                    r = fn()
                jax.block_until_ready(r)
                ms = (time.perf_counter() - t0) / reps * 1e3
                out[f"{name}_h{n_hist}_c{n_cand}_ms"] = round(ms, 3)
    return out


def main():
    _ensure_live_backend()
    t_setup = time.time()
    import jax

    from hyperopt_tpu.algos import tpe, tpe_device

    platform = jax.devices()[0].platform
    domain, trials = build_history_trials()
    hist = trials.history
    setup_s = time.time() - t_setup

    # --- XLA path: production suggest loop with growing history -------
    def one_suggest(i):
        tid = N_HISTORY + i
        docs = tpe.suggest(
            [tid], domain, trials, i, n_EI_candidates=N_EI_CANDIDATES
        )
        return docs[0]

    rng = np.random.default_rng(1)

    def complete(doc):
        # close the loop: the suggested trial completes and joins history
        from hyperopt_tpu.base import JOB_STATE_DONE, STATUS_OK

        doc["state"] = JOB_STATE_DONE
        doc["result"] = {"status": STATUS_OK, "loss": float(rng.standard_normal())}
        trials._insert_trial_docs([doc])
        trials.refresh()

    t0 = time.time()
    complete(one_suggest(0))  # compile warmup
    warmup_s = time.time() - t0

    dh = tpe_device.device_history_for(trials, domain.space)
    sync0, bytes0 = dh.sync_time, dh.bytes_uploaded
    t_suggest = 0.0
    for i in range(1, TIMED_SUGGESTS + 1):
        t0 = time.perf_counter()
        doc = one_suggest(i)
        t_suggest += time.perf_counter() - t0
        complete(doc)
    xla_per_suggest = t_suggest / TIMED_SUGGESTS
    host_transfer_ms = (dh.sync_time - sync0) / TIMED_SUGGESTS * 1e3
    host_bytes = (dh.bytes_uploaded - bytes0) / TIMED_SUGGESTS
    suggests_per_sec = 1.0 / xla_per_suggest
    ei_evals_per_sec = N_EI_CANDIDATES * N_LABELS / xla_per_suggest

    flops = _scorer_flops(dh, N_EI_CANDIDATES)
    achieved_tflops = flops / xla_per_suggest / 1e12

    # --- numpy baseline (reference-equivalent compute) ----------------
    nrng = np.random.default_rng(0)
    t0 = time.time()
    reps = 3
    for _ in range(reps):
        numpy_reference_suggest(trials.history, nrng)
    np_per_suggest = (time.time() - t0) / reps

    ab = _pallas_ab(platform)

    out = {
        "metric": "tpe_suggestions_per_sec_10k_history",
        "value": round(suggests_per_sec, 3),
        "unit": "suggest/s",
        "vs_baseline": round(np_per_suggest / xla_per_suggest, 2),
        "baseline_kind": "numpy reimplementation of reference compute (reference code unobtainable offline)",
        "platform": platform,
        "n_history": N_HISTORY,
        "n_labels": N_LABELS,
        "n_EI_candidates": N_EI_CANDIDATES,
        "xla_ms_per_suggest": round(xla_per_suggest * 1e3, 3),
        "numpy_baseline_ms_per_suggest": round(np_per_suggest * 1e3, 3),
        "candidate_EI_evals_per_sec": round(ei_evals_per_sec, 1),
        "host_transfer_ms_per_suggest": round(host_transfer_ms, 4),
        "host_bytes_per_suggest": int(host_bytes),
        "device_history_rebuilds": dh.full_rebuilds,
        "scorer_matmul_gflops_per_suggest": round(flops / 1e9, 2),
        "achieved_tflops": round(achieved_tflops, 4),
        "mfu_pct": (
            round(100.0 * achieved_tflops / TPU_PEAK_TFLOPS, 3)
            if platform == "tpu"
            else None
        ),
        "compile_warmup_s": round(warmup_s, 2),
        "setup_s": round(setup_s, 2),
    }
    if ab:
        out["scorer_ab_tpu"] = ab
    print(json.dumps(out))


if __name__ == "__main__":
    sys.exit(main())
