"""Headline benchmark: TPE candidate-EI evaluation throughput and
suggestions/sec at a 10k-trial history.

BASELINE.md metrics: "TPE suggestions/sec @ 10k-trial history" with the
north-star of ≥1000× the CPU reference's candidate-EI evaluations/sec.
The reference (gsmafra/hyperopt) is pure numpy on CPU and is not installed
in this image, so ``vs_baseline`` is measured against a faithful numpy
REIMPLEMENTATION of the same per-suggest computation (adaptive-Parzen fit
of l/g per label + O(candidates × history) log-density scoring) — the
exact math this framework runs as fused XLA/Pallas kernels, at the same
n_EI_candidates.  (Label it accordingly: this is *not* the reference's own
code path, which is unobtainable offline.)

Timing methodology (matters in this environment): the TPU chip sits
behind a network tunnel whose ``block_until_ready`` does NOT synchronize
and whose host↔device round trip is ~70 ms.  Naive per-call timing
therefore measures either nothing (no sync) or the tunnel (RTT >> device
time).  Device-plane numbers here are measured by iterating the kernel
inside ONE jitted ``lax.fori_loop`` with a data-dependent carry (so XLA
cannot hoist the body) and paying a single scalar readback, then
subtracting the separately-measured RTT.  The driver-loop number
(suggest/s through ``tpe.suggest``) is reported as-is and includes one
RTT per suggest — on a normal TPU host that term is ~100 µs, so it is
reported alongside ``tunnel_rtt_ms`` for interpretation.

The production loop grows the history by one completed trial per suggest,
so it exercises the steady state: the device-resident history
(``tpe_device.DeviceHistory``) absorbs each append incrementally and
``host_transfer_ms`` reports the measured host→device traffic per suggest
— the evidence that nothing re-uploads the 10k-trial history.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N, ...extras}

Runs on the default JAX platform (the real TPU chip under axon; CPU
elsewhere).  Do not run under tests/conftest.py (that forces CPU).
"""

import json
import os
import sys
import time
from functools import partial

import numpy as np

# Headline config (BASELINE.md); env knobs exist for quick smoke runs on
# CPU (e.g. BENCH_N_HISTORY=1000 BENCH_N_CAND=256 BENCH_TIMED=5).
N_HISTORY = int(os.environ.get("BENCH_N_HISTORY", 10_000))
N_LABELS = 5
N_EI_CANDIDATES = int(os.environ.get("BENCH_N_CAND", 8_192))
GAMMA = 0.25
LF = 25
TIMED_SUGGESTS = int(os.environ.get("BENCH_TIMED", 30))
LOOP_ITERS = int(os.environ.get("BENCH_LOOP_ITERS", 50))

# Hardware ceilings (v5e: 197 TFLOP/s bf16 MXU, 819 GB/s HBM; MFU is
# reported against the bf16 peak, i.e. conservatively low) live in ONE
# place — hyperopt_tpu.profiling.platform_peaks — with env overrides
# HYPEROPT_TPU_PEAK_TFLOPS / HYPEROPT_TPU_PEAK_HBM_GBPS for other chip
# generations; every bench field derives from that table.


def build_history_trials(n_history=None):
    """``n_history`` (default ``N_HISTORY``) completed trials over a
    5-label mixed space (doc-building cost excluded from timing)."""
    from hyperopt_tpu import Trials, hp
    from hyperopt_tpu.base import Domain

    n = N_HISTORY if n_history is None else int(n_history)
    space = {
        "lr": hp.loguniform("lr", np.log(1e-5), np.log(1.0)),
        "momentum": hp.uniform("momentum", 0.0, 1.0),
        "width": hp.quniform("width", 32, 1024, 32),
        "sigma": hp.lognormal("sigma", 0.0, 1.0),
        "z": hp.normal("z", 0.0, 3.0),
    }
    domain = Domain(lambda c: 0.0, space)
    rng = np.random.default_rng(0)
    vals, _ = domain.space.sample_batch(0, n)
    losses = rng.standard_normal(n)
    docs = []
    for i in range(n):
        docs.append(_done_doc(i, {k: float(vals[k][i]) for k in vals}, float(losses[i])))
    trials = Trials()
    trials._insert_trial_docs(docs)
    trials.refresh()
    return domain, trials


def _done_doc(tid, config, loss):
    from hyperopt_tpu.base import JOB_STATE_DONE, STATUS_OK

    return {
        "tid": tid,
        "spec": None,
        "result": {"status": STATUS_OK, "loss": loss},
        "misc": {
            "tid": tid,
            "cmd": None,
            "idxs": {k: [tid] for k in config},
            "vals": {k: [v] for k, v in config.items()},
        },
        "state": JOB_STATE_DONE,
        "owner": None,
        "book_time": None,
        "refresh_time": None,
        "exp_key": None,
    }


def _derived_cap_b():
    """bucket(n_below) at the bench history size — derived, not hardcoded
    (n_below = min(ceil(γ·√N), linear_forgetting), ap_split_trials)."""
    from hyperopt_tpu.ops import parzen as parzen_ops

    n_below = min(int(np.ceil(GAMMA * np.sqrt(N_HISTORY))), LF)
    return parzen_ops.bucket(max(n_below, 1))


# ---------------------------------------------------------------------
# numpy reference implementation (CPU-hyperopt-equivalent compute)
# ---------------------------------------------------------------------


def _np_parzen(obs, prior_mu, prior_sigma, lf=LF):
    n = len(obs)
    if n == 0:
        return np.array([1.0]), np.array([prior_mu]), np.array([prior_sigma])
    order = np.argsort(obs)
    srtd = obs[order]
    pos = int(np.searchsorted(srtd, prior_mu))
    mus = np.insert(srtd, pos, prior_mu)
    k = n + 1
    sigma = np.zeros(k)
    if k > 1:
        gaps_l = np.diff(mus, prepend=mus[0])
        gaps_r = np.diff(mus, append=mus[-1])
        sigma = np.maximum(gaps_l, gaps_r)
        sigma[0] = mus[1] - mus[0]
        sigma[-1] = mus[-1] - mus[-2]
    sigma = np.clip(sigma, prior_sigma / min(100.0, 1.0 + k), prior_sigma)
    sigma[pos] = prior_sigma
    if lf and n > lf:
        w = np.concatenate([np.linspace(1.0 / n, 1.0, n - lf), np.ones(lf)])
    else:
        w = np.ones(n)
    w = w[order]
    weights = np.insert(w, pos, 1.0)
    weights /= weights.sum()
    return weights, mus, sigma


def _np_gmm_lpdf(x, w, mu, sigma):
    # O(C x K) — the reference's hot loop
    mahal = ((x[:, None] - mu[None, :]) / sigma[None, :]) ** 2
    comp = -0.5 * mahal - np.log(sigma * np.sqrt(2 * np.pi))[None, :] + np.log(w)[None, :]
    m = comp.max(axis=1, keepdims=True)
    return (m[:, 0]) + np.log(np.exp(comp - m).sum(axis=1))


def numpy_reference_suggest(hist, rng, n_cand=N_EI_CANDIDATES):
    losses = hist.losses
    n = len(losses)
    n_below = min(int(np.ceil(GAMMA * np.sqrt(n))), LF)
    order = np.argsort(losses, kind="stable")
    below_tids = hist.loss_tids[order[:n_below]]
    out = {}
    ei_evals = 0
    for label, tids in hist.idxs.items():
        obs = np.asarray(hist.vals[label], dtype=np.float64)
        mask = np.isin(tids, below_tids)
        b, a = obs[mask], obs[~mask]
        wb, mb, sb = _np_parzen(b, float(obs.mean()), float(obs.std() + 1e-3))
        wa, ma, sa = _np_parzen(a, float(obs.mean()), float(obs.std() + 1e-3))
        comp = rng.choice(len(wb), size=n_cand, p=wb)
        cand = rng.normal(mb[comp], sb[comp])
        score = _np_gmm_lpdf(cand, wb, mb, sb) - _np_gmm_lpdf(cand, wa, ma, sa)
        ei_evals += n_cand * (len(wb) + len(wa))
        out[label] = cand[np.argmax(score)]
    return out, ei_evals


def _ensure_live_backend():
    """Guard against a wedged TPU tunnel: probe device init in a throwaway
    subprocess; on hang/failure re-exec this bench on CPU.  (Setting
    JAX_PLATFORMS alone is not enough — the axon sitecustomize overrides
    the config in every process — so the axon env trigger is removed.)"""
    import subprocess

    if os.environ.get("BENCH_BACKEND_PROBED"):
        return
    try:
        subprocess.run(
            [sys.executable, "-c", "import jax; jax.devices()"],
            timeout=180,
            capture_output=True,
            check=True,
        )
        os.environ["BENCH_BACKEND_PROBED"] = "1"
        return
    except (subprocess.TimeoutExpired, subprocess.CalledProcessError):
        pass
    print("bench: TPU backend unreachable, falling back to CPU", file=sys.stderr)
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)  # disables axon registration
    env["JAX_PLATFORMS"] = "cpu"
    env["BENCH_BACKEND_PROBED"] = "1"
    os.execve(sys.executable, [sys.executable] + sys.argv, env)


def _apply_cpu_bounds(platform):
    """Bound the wall clock off-TPU (~10 min, not ~45) by shrinking
    SAMPLE counts only — BENCH_TIMED / LOOP_ITERS / BATCH_REPS reduce
    statistical weight, never what a metric measures (batched_k stays 32
    for that reason); explicit env settings are honored.  Applies to any
    CPU run: the probe-failure fallback and a deliberate
    JAX_PLATFORMS=cpu invocation alike."""
    global TIMED_SUGGESTS, LOOP_ITERS
    if platform == "tpu":
        return {}
    if os.environ.get("BENCH_TIMED") is None:
        TIMED_SUGGESTS = 10
    if os.environ.get("BENCH_LOOP_ITERS") is None:
        LOOP_ITERS = 15
    reps = {} if os.environ.get("BENCH_BATCH_REPS") else {"breps": 2}
    return reps


# ---------------------------------------------------------------------
# Device-plane timing harness (tunnel-safe; see module docstring)
# ---------------------------------------------------------------------


def _measure_rtt():
    """Scalar-readback round trip of a trivial program (tunnel latency)."""
    import jax
    import jax.numpy as jnp

    f = jax.jit(lambda x: x[0, 0])
    x = jnp.zeros((8, 128), jnp.float32)
    float(f(x))  # compile
    times = []
    for _ in range(5):
        t0 = time.perf_counter()
        float(f(x))
        times.append(time.perf_counter() - t0)
    return min(times)


def _bench_in_graph(step, args, iters, rtt):
    """Time ``step(carry, *args) -> f32 carry`` iterated in one jitted
    fori_loop, single scalar readback, RTT subtracted.  The carry must
    feed back into the computation so XLA cannot hoist the body.

    If the device signal is small relative to the tunnel RTT, the loop
    count escalates (up to 3x doubling-by-8) until the total run is at
    least 3x the RTT — otherwise RTT jitter could swallow the sample and
    publish a garbage rate."""
    import jax
    import jax.numpy as jnp

    def timed(n):
        @jax.jit
        def run(c0, *a):
            return jax.lax.fori_loop(0, n, lambda i, c: step(c, *a), c0)

        float(run(jnp.float32(0.0), *args))  # compile + warm
        best = float("inf")
        for _ in range(2):
            t0 = time.perf_counter()
            float(run(jnp.float32(0.0), *args))
            best = min(best, time.perf_counter() - t0)
        return best

    for _ in range(3):
        best = timed(iters)
        if best >= 3.0 * rtt or rtt < 1e-3:
            break
        iters *= 8
    return max(best - rtt, 0.05 * best) / iters


def _scorer_flops(dh, n_cand):
    """MXU matmul FLOPs per suggest in the pair scorer: F[C,3] @ P[3,K]
    per continuous family label (2·3·C·K), K = both padded mixtures."""
    cap_b = _derived_cap_b()
    flops = 0
    for fam in dh.families.values():
        if fam.key[0] != "cont":
            continue
        K = (cap_b + 1) + (fam.cap + 1)
        flops += fam.L * 2 * 3 * n_cand * K
    return flops


def _scorer_cost(dh, n_cand, scorer="xla"):
    """{flops, bytes, mxu_flops} of one suggest's PAIR-SCORER work — the
    HBM-traffic extension of :func:`_scorer_flops`, restricted to the
    non-quantized continuous families (the same set ``suggest_ei_evals``
    credits, so rate / cost / roofline all describe identical work; the
    memory model per scorer implementation lives in
    ``hyperopt_tpu.ops.score.pair_score_cost``)."""
    from hyperopt_tpu.ops.score import pair_score_cost

    cap_b = _derived_cap_b()
    out = {"flops": 0.0, "bytes": 0.0, "mxu_flops": 0.0}
    for fam in dh.families.values():
        if fam.key[0] != "cont" or fam.quantized:
            continue
        K = (cap_b + 1) + (fam.cap + 1)
        cost = pair_score_cost(n_cand, K, scorer)
        for key in out:
            out[key] += fam.L * cost[key]
    return out


def device_headline_fields(cost, suggest_ei_evals, device_ei_rate,
                           platform, scorer):
    """The device-plane headline fields, roofline-attributed.

    THE null contract (VERDICT r6 #4): a field whose measurement is
    unavailable is ``null`` with a non-null ``unmeasured_reason`` —
    never a silent ``0.0`` placeholder (``BENCH_TPU_100k.json``
    originally shipped ``achieved_tflops: 0.0`` / ``mfu_pct: 0.0``
    because the scorer A/B had been skipped).

    - ``achieved_tflops`` / ``achieved_GBps`` / ``binding_ceiling`` /
      ``roofline_pct*``: from the full analytical scorer cost model
      (``_scorer_cost``) against the platform roofline ceilings
      (``profiling.platform_peaks`` — nominal, flagged, off-TPU);
    - ``mfu_pct`` keeps its historical meaning — matmul-only FLOPs
      against the TPU bf16 MXU peak — and is null off-TPU
      (``mfu_pct_reason`` says why).
    """
    from hyperopt_tpu import profiling

    peaks = profiling.platform_peaks(platform)
    out = {
        "device_scorer_ms_per_suggest": None,
        "achieved_tflops": None,
        "achieved_GBps": None,
        "mfu_pct": None,
        "mfu_pct_reason": (
            None if platform == "tpu" else
            "mfu_pct is defined against the TPU bf16 MXU peak; "
            f"platform is {platform}"
        ),
        "binding_ceiling": None,
        "roofline_pct": None,
        "roofline_pct_bw": None,
        "roofline_pct_mxu": None,
        "roofline_scorer": scorer,
        "scorer_traffic_gbytes_per_suggest": None,
        "peaks": {
            k: peaks[k]
            for k in ("peak_tflops", "peak_hbm_GBps", "source")
        },
        "unmeasured_reason": None,
    }
    if device_ei_rate <= 0 or not suggest_ei_evals:
        reasons = []
        if device_ei_rate <= 0:
            reasons.append(
                "device-plane scorer rate unavailable"
                + (
                    " (scorer A/B disabled: BENCH_AB=0)"
                    if os.environ.get("BENCH_AB") == "0" else ""
                )
            )
        if not suggest_ei_evals:
            reasons.append("no non-quantized continuous families")
        out["unmeasured_reason"] = "; ".join(reasons)
        return out
    scorer_s = suggest_ei_evals / device_ei_rate
    roof = profiling.roofline(cost["flops"], cost["bytes"], scorer_s, peaks)
    out["device_scorer_ms_per_suggest"] = round(scorer_s * 1e3, 3)
    out["achieved_tflops"] = round(roof["achieved_tflops"], 4)
    out["achieved_GBps"] = round(roof["achieved_GBps"], 2)
    out["binding_ceiling"] = roof["binding_ceiling"]
    out["roofline_pct"] = round(roof["roofline_pct"], 3)
    out["roofline_pct_bw"] = round(roof["roofline_pct_bw"], 3)
    out["roofline_pct_mxu"] = round(roof["roofline_pct_mxu"], 3)
    out["scorer_traffic_gbytes_per_suggest"] = round(
        cost["bytes"] / 1e9, 4
    )
    if platform == "tpu":
        # same (possibly env-overridden) peak as the roofline fields —
        # the two must never disagree within one artifact
        out["mfu_pct"] = round(
            100.0 * (cost["mxu_flops"] / scorer_s / 1e12)
            / peaks["peak_tflops"], 3,
        )
    return out


def _tpu_smoke():
    """Tiny hardware checks before timing (VERDICT r3 #6): the Pallas
    probe (both kernels, interpret=False) and a scorer-vs-float64 parity
    check on the live backend.  Raises on failure so a broken lowering
    fails the bench loudly instead of timing a crash."""
    import jax
    import jax.numpy as jnp

    from hyperopt_tpu.algos import tpe
    from hyperopt_tpu.ops.score import pair_params, pair_score

    scorer = tpe._use_pallas()  # runs the probe on TPU
    rng = np.random.default_rng(0)

    def mk(k):
        w = rng.random(k).astype(np.float32)
        w /= w.sum()
        return (
            jnp.asarray(w),
            jnp.asarray(rng.normal(size=k).astype(np.float32)),
            jnp.asarray((0.1 + rng.random(k)).astype(np.float32)),
        )

    kb, ka, C = 25, 999, 512
    params = pair_params(*mk(kb), *mk(ka))
    z = jnp.asarray(rng.normal(size=C).astype(np.float32))
    got = np.asarray(pair_score(z, params, kb))
    zf = np.asarray(z, np.float64)
    P = np.asarray(params, np.float64)
    f = np.stack([zf * zf, zf, np.ones_like(zf)], 1)
    comp = f @ P

    def lse(c):
        m = c.max(1)
        return m + np.log(np.exp(c - m[:, None]).sum(1))

    ref = lse(comp[:, :kb]) - lse(comp[:, kb:])
    err = float(np.max(np.abs(got - ref)))
    if not np.isfinite(err) or err > 1e-2:
        raise RuntimeError(f"scorer precision smoke failed: max_err={err}")
    from hyperopt_tpu.ops import pallas_gmm

    # report what the scorer paths will actually USE (the unified
    # resolver), not the raw measured globals — None placeholders never
    # reach the artifact, and the two paths can no longer diverge
    # silently (the measured values still differ only when both were
    # probed and disagreed; resolve_fma applies the single-probe
    # fallback either way).  Each default is stamped WITH its measured
    # basis (env pin / own probe / sibling-probe fallback / unprobed)
    # so two artifacts showing different defaults are explainable
    # rather than contradictory.
    return scorer, err, (
        pallas_gmm.resolve_fma("batched"),
        pallas_gmm.resolve_fma("unbatched"),
        {
            "batched": pallas_gmm.resolve_fma_basis("batched"),
            "unbatched": pallas_gmm.resolve_fma_basis("unbatched"),
        },
    )


def _device_scorer_bench(rtt, cap_b, platform):
    """Device-plane A/B of the two scorers at production shapes, via the
    in-graph harness.  Returns (table, headline) where headline is the
    best EI-evals/sec at the BASELINE config (10k history; candidates
    8192, plus 65536 on TPU only — the CPU fallback skips it, ~10 s/iter
    for an identical GEI/s reading).

    EI evals are counted over REAL mixture components only (history + 1
    prior per side) — padding lanes are device overhead, not credited
    work — so the ratio against the numpy baseline compares identical
    mathematics.  The Pallas kernel is skipped off-TPU (no Mosaic) and
    the whole A/B can be disabled with BENCH_AB=0."""
    import jax.numpy as jnp

    from hyperopt_tpu.ops import parzen as parzen_ops
    from hyperopt_tpu.ops.pallas_gmm import pair_score_pallas
    from hyperopt_tpu.ops.score import pair_params, pair_score

    if os.environ.get("BENCH_AB") == "0":
        return None, 0.0
    out = {}
    headline = 0.0
    rng = np.random.default_rng(0)
    for n_hist in (1_000, N_HISTORY):
        cap = parzen_ops.bucket(n_hist)
        obs = jnp.asarray(rng.normal(size=cap).astype(np.float32))
        wa, ma, sa = parzen_ops.adaptive_parzen_normal_padded(
            obs, n_hist, jnp.float32(1.0), jnp.float32(0.0), jnp.float32(10.0), LF
        )
        wb, mb, sb = parzen_ops.adaptive_parzen_normal_padded(
            obs[:cap_b], min(LF, n_hist), jnp.float32(1.0), jnp.float32(0.0),
            jnp.float32(10.0), LF,
        )
        params = pair_params(wb, mb, sb, wa, ma, sa)
        k_below = int(wb.shape[0])
        # real components: n_hist obs + 1 prior (above), LF obs + 1 (below)
        k_real = (min(LF, n_hist) + 1) + (n_hist + 1)
        scorers = [("xla", pair_score)]
        if platform == "tpu":
            # mxu vs fma: same online-logsumexp kernel, quadratic evaluated
            # on the MXU (multi-pass HIGHEST dot, contraction dim 3 padded
            # to 128) vs as VPU broadcast FMAs (exact f32, no dead lanes)
            scorers.append(("pallas", partial(pair_score_pallas, fma=False)))
            scorers.append(("pallas_fma", partial(pair_score_pallas, fma=True)))
        # the 65536-candidate point is TPU-only: on the CPU fallback it
        # costs ~10 s/iter while reporting the same GEI/s as c=8192
        cand_sizes = (8_192, 65_536) if platform == "tpu" else (8_192,)
        for n_cand in cand_sizes:
            z = jnp.asarray(rng.normal(size=n_cand).astype(np.float32))
            for name, fn in scorers:
                def step(c, z, params, fn=fn):
                    # carry perturbs every candidate -> body not hoistable
                    s = fn(z + c * jnp.float32(1e-7), params, k_below)
                    return s[0] * jnp.float32(1e-7)

                per = _bench_in_graph(step, (z, params), LOOP_ITERS, rtt)
                ei_rate = n_cand * k_real / per
                out[f"{name}_h{n_hist}_c{n_cand}_ms"] = round(per * 1e3, 4)
                out[f"{name}_h{n_hist}_c{n_cand}_gei_s"] = round(ei_rate / 1e9, 2)
                if n_hist == N_HISTORY:
                    headline = max(headline, ei_rate)
    return out, headline


def _import_script(name):
    """Import a module from scripts/ (the bench sections delegate to the
    standalone campaign scripts so every committed BENCH_*.json artifact
    is reproducible through bench.py)."""
    scripts_dir = os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "scripts"
    )
    sys.path.insert(0, scripts_dir)
    try:
        return __import__(name)
    finally:
        # remove by value: some scripts prepend the repo root at import
        # time, so pop(0) would strip the wrong entry
        try:
            sys.path.remove(scripts_dir)
        except ValueError:
            pass


def wallclock_section(argv):
    """``python bench.py --wallclock [--quick]``: the wall-clock-to-target
    benchmark for the pipelined suggest engine (BASELINE.md's
    "wall-clock-to-equal-quality" metric).  Delegates to
    scripts/bench_walltime.py, which writes BENCH_WALLCLOCK.json; this
    entry point exists so every committed BENCH_*.json artifact is
    reproducible through bench.py."""
    return _import_script("bench_walltime").main(argv)


def lint_section(argv):
    """``python bench.py --lint [--quick]``: static-analysis smoke —
    self-lint (race + durability passes over the auto-discovered
    package surface + static program checks incl. partition pin sites
    and dispatch containers) plus a short recompilation audit of the
    fused TPE suggest program on CPU (100 trials, 40 with ``--quick``;
    the full 200-trial tier runs via ``scripts/lint.py --audit``).
    Prints ONE JSON line like the other bench sections."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    n_audit = 40 if "--quick" in argv else 100
    t0 = time.time()
    from hyperopt_tpu.analysis import (
        Severity,
        audit_tpe_run,
        discover_race_files,
        lint_repo,
        package_files,
    )

    pkg = package_files()
    race_files = discover_race_files(paths=pkg)
    diags = lint_repo(static_only=True, paths=pkg, race_paths=race_files)
    aud = audit_tpe_run(n_trials=n_audit)
    diags += aud.diagnostics()
    n_err = sum(1 for d in diags if d.severity == Severity.ERROR)
    out = {
        "metric": "lint_smoke",
        "value": len(diags),
        "unit": "diagnostics",
        "ok": n_err == 0,
        "errors": n_err,
        # coverage of the auto-discovered pass surfaces (the old
        # hand-registry could silently shrink; these cannot)
        "race_files": len(race_files),
        "durability_files": len(pkg),
        "audit_trials": n_audit,
        "audit_traces": aud.n_traces,
        "audit_program_keys": aud.n_programs,
        "audit_buckets": aud.bucket_summary(),
        "elapsed_s": round(time.time() - t0, 2),
    }
    if diags:
        out["rules"] = sorted({d.rule for d in diags})
    print(json.dumps(out))
    return 0 if n_err == 0 else 1


def chaos_section(argv):
    """``python bench.py --chaos [--quick]``: fault-tolerance smoke — a
    short seeded chaos campaign on CPU (scripts/chaos_campaign.py):
    worker kills, torn locks, delayed/duplicated results, objective
    errors/hangs, and synthetic device errors injected against a
    FileTrials run and a serial TPE run; asserts zero stranded
    reservations, reconciled fault accounting, and best-trial equality
    with the fault-free twin.  Prints ONE JSON line like the other
    bench sections."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    chaos_campaign = _import_script("chaos_campaign")
    quick = "--quick" in argv
    t0 = time.time()
    report = chaos_campaign.run_campaign(
        n_trials=30 if quick else 60, n_workers=2, quick=quick
    )
    queue_phase = report["phases"][0]
    device_phase = report["phases"][1]
    out = {
        "metric": "chaos_smoke",
        "value": report["total_injected"],
        "unit": "injected_faults",
        "ok": report["ok"],
        "queue_ok": queue_phase["ok"],
        "device_ok": device_phase["ok"],
        "stranded": queue_phase["stranded_running"]
        + queue_phase["stranded_locks"],
        "worker_respawns": queue_phase["worker_respawns"],
        "best_matches_fault_free": queue_phase["best_matches_fault_free"]
        and device_phase["best_matches_fault_free"],
        "elapsed_s": round(time.time() - t0, 2),
    }
    print(json.dumps(out))
    return 0 if report["ok"] else 1


def chaos_serve_section(argv):
    """``python bench.py --chaos-serve [--quick]``: service-plane
    exactly-once smoke — a short seeded chaos-serve campaign
    (scripts/chaos_serve_campaign.py) on CPU: server SIGKILLs (scheduled
    and mid-torn-write), connection resets before/after response commit,
    and slow-loris clients against retrying idempotent clients; asserts
    zero lost/duplicated trials, fsck clean, per-study trajectories
    identical to the fault-free twin, and byte-identical journal
    replays.  Prints ONE JSON line like the other bench sections."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    chaos_serve = _import_script("chaos_serve_campaign")
    quick = "--quick" in argv
    t0 = time.time()
    report = chaos_serve.run_campaign(
        n_studies=4 if quick else 8,
        n_trials=6 if quick else 12,
        min_kills=2 if quick else 3,
        quick=quick,
    )
    out = {
        "metric": "chaos_serve_smoke",
        "value": report["total_injected"],
        "unit": "injected_faults",
        "ok": report["ok"],
        "server_kills": report["server_kills"],
        "lost_trials": report["integrity"]["lost_trials"],
        "duplicated_trials": report["integrity"]["duplicated_trials"],
        "trajectories_match": report["trajectories_match_fault_free"],
        "fsck_clean": report["fsck_after_repair"]["clean"],
        "replay_ok": report["replay"]["ok"],
        "errors": report["errors"],
        "elapsed_s": round(time.time() - t0, 2),
    }
    print(json.dumps(out))
    return 0 if report["ok"] else 1


def serve_section(argv):
    """``python bench.py --serve [--quick]``: optimization-service smoke —
    a short seeded multi-study loadgen run on CPU
    (scripts/serve_loadgen.py): 8 concurrent studies driven through the
    HTTP server, asserting every study completes, mean batch occupancy
    > 1.5 suggest-requests/dispatch, and fewer fused device dispatches
    than device-plane suggest requests.  Prints ONE JSON line like the
    other bench sections."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    serve_loadgen = _import_script("serve_loadgen")
    quick = "--quick" in argv
    t0 = time.time()
    report = serve_loadgen.run_loadgen(
        n_studies=8, n_trials=6 if quick else 12
    )
    out = {
        "metric": "serve_smoke",
        "value": report["mean_batch_occupancy"],
        "unit": "suggests/dispatch",
        "ok": report["ok"],
        "n_dispatches": report["n_dispatches"],
        "n_batched_suggests": report["n_batched_suggests"],
        "suggest_p50_ms": report["suggest_p50_ms"],
        "suggest_p99_ms": report["suggest_p99_ms"],
        "errors": report["errors"],
        "elapsed_s": round(time.time() - t0, 2),
    }
    print(json.dumps(out))
    return 0 if report["ok"] else 1


def trace_section(argv):
    """``python bench.py --trace [--quick]``: request-tracing smoke — the
    seeded multi-study loadgen with end-to-end tracing on (sample 1.0),
    aggregated by scripts/trace_report.py; asserts the tiling phase
    spans cover >= 90% of every sampled suggest's server wall-time and
    that every XLA compile event observed carries a (trial-bucket,
    family) key and the trace id that paid for it.  Prints ONE JSON
    line like the other bench sections."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    serve_loadgen = _import_script("serve_loadgen")
    quick = "--quick" in argv
    t0 = time.time()
    bench, trep = serve_loadgen.run_traced(
        n_studies=8, n_trials=6 if quick else 12, seed=0,
        batch_window=0.004, trace_sample=1.0,
        overhead_check="--overhead" in argv,
    )
    out = {
        "metric": "trace_smoke",
        "value": trep["coverage"]["min"],
        "unit": "min_coverage",
        "ok": trep["ok"],
        "n_suggest_traces": trep["n_suggest_traces"],
        "coverage_mean": trep["coverage"]["mean"],
        "n_compile_events": trep["compile_events"]["n"],
        "compiles_attributed": trep["compile_events"]["attributed"],
        "suggest_p50_ms": trep["suggest_latency"]["p50_ms"],
        "suggest_p99_ms": trep["suggest_latency"]["p99_ms"],
        "elapsed_s": round(time.time() - t0, 2),
    }
    if "overhead" in trep:
        out["p50_regression_frac"] = trep["overhead"]["p50_regression_frac"]
    print(json.dumps(out))
    return 0 if trep["ok"] else 1


def study_health_section(argv):
    """``python bench.py --study-health [--quick]``: search-health
    observability smoke — runs the SH5xx study report
    (scripts/study_report.py) on CPU and writes ``STUDY_HEALTH.json``
    (healthy QUALITY.md domains all OK, one seeded degenerate fixture
    per rule flagged with its intended id, the zero-extra-dispatch
    assertion over the fused EI statistics, and the host-side overhead
    check <5%).  A quick run writes a separate file so CI can never
    clobber the committed full artifact (the PR 7 convention).  Prints
    ONE JSON line like the other bench sections."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    study_report = _import_script("study_report")
    quick = "--quick" in argv
    out_path = "STUDY_HEALTH.quick.json" if quick else "STUDY_HEALTH.json"
    if "--out" in argv:
        out_path = argv[argv.index("--out") + 1]
    t0 = time.time()
    report = study_report.run_report(quick=quick)
    study_report.write_report(report, out_path)
    out = {
        "metric": "study_health_smoke",
        "value": sum(
            1 for v in report["fixtures"].values() if v["ok"]
        ),
        "unit": "fixtures_flagged",
        "ok": report["ok"],
        "healthy_states": {
            k: v["state"] for k, v in report["healthy"].items()
        },
        "extra_dispatches": report["zero_dispatch"]["extra_dispatches"],
        "overhead_p50_regression_frac": (
            report["overhead"]["p50_regression_frac"]
            if report["overhead"] else None
        ),
        "elapsed_s": round(time.time() - t0, 2),
    }
    print(json.dumps(out))
    return 0 if report["ok"] else 1


def slo_section(argv):
    """``python bench.py --slo [--quick]``: SLO-guardrail smoke — runs
    the SL6xx acceptance report (scripts/slo_report.py) on CPU and
    writes ``SLO_SERVE.json`` (SLO-gated healthy loadgen with the
    warm/cold latency split and storage-plane reconciliation, one
    seeded forced-breach fixture per rule each firing its intended id
    with a parseable flight-recorder bundle, and the guardrails-on
    overhead check <5%).  A quick run writes a separate file so CI can
    never clobber the committed full artifact (the PR 7 convention).
    Prints ONE JSON line like the other bench sections."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    slo_report = _import_script("slo_report")
    quick = "--quick" in argv
    out_path = "SLO_SERVE.quick.json" if quick else "SLO_SERVE.json"
    if "--out" in argv:
        out_path = argv[argv.index("--out") + 1]
    t0 = time.time()
    report = slo_report.run_report(quick=quick)
    slo_report.write_report(report, out_path)
    out = {
        "metric": "slo_smoke",
        "value": sum(1 for v in report["fixtures"].values() if v["ok"]),
        "unit": "fixtures_breached",
        "ok": report["ok"],
        "healthy_ok": report["healthy"]["ok"],
        "healthy_rules": {
            r["rule"]: r["status"] for r in report["healthy"]["rules"]
        },
        "reconciliation_ok": (
            report["healthy"]["reconciliation"]["ok"]
        ),
        "recorder_roundtrip_ok": report["recorder_roundtrip"]["ok"],
        "overhead_p50_regression_frac": (
            report["overhead"]["p50_regression_frac"]
            if report["overhead"] else None
        ),
        "elapsed_s": round(time.time() - t0, 2),
    }
    print(json.dumps(out))
    return 0 if report["ok"] else 1


def warmup_section(argv):
    """``python bench.py --warmup [--quick]``: compile-plane smoke —
    runs the cold-start vs warmed-restart A/B (scripts/warmup_report.py)
    on CPU and writes ``WARMUP_SERVE.json`` (ledger-driven AOT warmup
    covers the campaign's bucket x family grid before /readyz, zero
    request-path compiles after ready on the warmed run with SL607
    clean, kill -9 restart warmup a small fraction of the cold compile
    cost via the persistent XLA cache, served_cold containment fully
    trace-attributed, compile-plane overhead <5%).  A quick run writes
    a separate file so CI can never clobber the committed full artifact
    (the PR 7 convention).  Prints ONE JSON line like the other bench
    sections."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    warmup_report = _import_script("warmup_report")
    quick = "--quick" in argv
    out_path = "WARMUP_SERVE.quick.json" if quick else "WARMUP_SERVE.json"
    if "--out" in argv:
        out_path = argv[argv.index("--out") + 1]
    t0 = time.time()
    report = warmup_report.run_report(quick=quick)
    warmup_report.write_report(report, out_path)
    out = {
        "metric": "warmup_smoke",
        "value": report["coverage"]["frac"],
        "unit": "grid_coverage",
        "ok": report["ok"],
        "n_cold_after_ready": report["warmed"]["n_cold_after_ready"],
        "restart_ratio": report["restart_ratio"]["warmed_over_cold"],
        "served_cold_attributed": report["served_cold"]["attributed"],
        "overhead_p50_regression_frac": (
            report["overhead"]["p50_regression_frac"]
        ),
        "errors": report["errors"],
        "elapsed_s": round(time.time() - t0, 2),
    }
    print(json.dumps(out))
    return 0 if report["ok"] else 1


def sharded_section(argv):
    """``python bench.py --sharded [--quick]``: mesh execution mode.

    ``--quick`` is the CI smoke: a forced 8-device virtual CPU mesh,
    small k grid, writes ``BENCH_TPU_sharded.quick.json`` — every mesh
    code path (sharded pair scoring, replicated history placement,
    per-device limiter attribution) executes in tier-1 without a TPU.
    WITHOUT ``--quick`` this is the real capture: the full sweep on the
    LIVE backend (run it on the multi-chip TPU host), writing
    ``BENCH_TPU_sharded.json`` with the same ``ok``/coverage fields the
    artifact guard asserts.  Prints ONE JSON line like the other bench
    sections."""
    quick = "--quick" in argv
    if quick:
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count=8"
            ).strip()
    sweep = _import_script("batched_suggest_sweep")
    # a quick smoke must not clobber the committed full-run artifact
    out_path = (
        "BENCH_TPU_sharded.quick.json" if quick else "BENCH_TPU_sharded.json"
    )
    if "--out" in argv:
        out_path = argv[argv.index("--out") + 1]
    t0 = time.time()
    if quick:
        report = sweep.run_sweep(
            ks=(8, 32), reps=2, mesh_arms=(None, "auto"),
            n_history=2_000, n_cand=512,
        )
    else:
        report = sweep.run_sweep(mesh_arms=(None, "auto"))
    report["quick"] = quick
    import jax

    n_devices = int(jax.device_count())
    mesh_rows = [r for r in report["rows"] if r["mesh"] != "off"]
    off_rows = [r for r in report["rows"] if r["mesh"] == "off"]
    ok = (
        bool(mesh_rows) and bool(off_rows)
        and all(r["suggests_per_sec"] > 0 for r in report["rows"])
        # the mesh arm's dispatches really spanned every local chip
        and all(len(r["per_device"]) == n_devices for r in mesh_rows)
        and all(
            row["n_dispatches"] > 0 for r in mesh_rows
            for row in r["per_device"].values()
        )
    )
    report["ok"] = ok
    with open(out_path, "w") as f:
        json.dump(report, f, indent=1, sort_keys=True)
        f.write("\n")
    best_mesh = max(
        (r["suggests_per_sec"] for r in mesh_rows), default=0.0
    )
    out = {
        "metric": "sharded_suggest_smoke",
        "value": best_mesh,
        "unit": "suggests/s",
        "ok": ok,
        "platform": report["platform"],
        "n_devices": n_devices,
        "mesh_arms": report["mesh_arms"],
        "rows": len(report["rows"]),
        "artifact": out_path,
        "elapsed_s": round(time.time() - t0, 2),
    }
    print(json.dumps(out))
    return 0 if ok else 1


def device_profile_section(argv):
    """``python bench.py --device-profile [--quick]``: device-plane
    observability smoke — runs the roofline-profiled suggest workload
    (scripts/device_report.py) on CPU and writes ``DEVICE_PROFILE.json``
    (per-signature roofline table, binding-ceiling histogram, duty
    cycle, memory watermarks, observer-overhead check); asserts every
    dispatch carries a non-null binding ceiling and roofline_pct.  A
    real-hardware capture runs ``scripts/device_report.py`` directly on
    the TPU host.  Prints ONE JSON line like the other bench
    sections."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    device_report = _import_script("device_report")
    quick = "--quick" in argv
    # a quick smoke must not clobber the committed full-run artifact
    # (the CI default is --quick, run from the repo root)
    out_path = "DEVICE_PROFILE.quick.json" if quick else "DEVICE_PROFILE.json"
    if "--out" in argv:
        out_path = argv[argv.index("--out") + 1]
    t0 = time.time()
    report = device_report.run_profile(
        quick=quick, overhead=not quick or "--overhead" in argv
    )
    device_report.write_report(report, out_path)
    out = {
        "metric": "device_profile_smoke",
        "value": report["n_dispatches"],
        "unit": "dispatches",
        "ok": report["ok"],
        "platform": report["platform"],
        "n_signatures": len(report["signatures"]),
        "unattributed_dispatches": report["unattributed_dispatches"],
        "duty_cycle": report["duty_cycle"],
        "binding_ceiling_hist": report["binding_ceiling_hist"],
        "elapsed_s": round(time.time() - t0, 2),
    }
    if report.get("overhead"):
        out["observer_p50_regression_frac"] = (
            report["overhead"]["p50_regression_frac"]
        )
    print(json.dumps(out))
    return 0 if report["ok"] else 1


def fused_section(argv):
    """``python bench.py --fused [--quick]``: fused-mega-kernel smoke —
    runs scripts/fused_report.py.  ``--quick`` (the CI default) forces
    interpret mode on CPU and asserts the STRUCTURAL contract: bitwise
    fused==reference winners across the shape grid (incl. the
    100k-tiled case), trial-for-trial trajectory identity against the
    unfused path, and one-trace-per-bucket under the
    RecompilationAuditor; a full run on the TPU host additionally
    measures the fused-vs-unfused EI-evals/s headline.  Writes
    ``BENCH_TPU_fused[.quick].json`` (a quick run never clobbers the
    committed full artifact — the PR 7 convention).  Prints ONE JSON
    line like the other bench sections."""
    if "--quick" in argv:
        # the quick smoke's contract is the CPU-checkable parity tier:
        # pin the CPU backend and force the Pallas interpreter even if
        # a TPU is visible.  A FULL run must keep the live backend —
        # it exists to measure the fused-vs-unfused headline on TPU
        # (the sharded_section convention).
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        os.environ["HYPEROPT_TPU_FUSED_INTERPRET"] = "1"
    fused_report = _import_script("fused_report")
    quick = "--quick" in argv
    out_path = (
        "BENCH_TPU_fused.quick.json" if quick else "BENCH_TPU_fused.json"
    )
    if "--out" in argv:
        out_path = argv[argv.index("--out") + 1]
    t0 = time.time()
    report = fused_report.run_fused(quick=quick)
    fused_report.write_report(report, out_path)
    exact = [p for p in report["parity"] if not p["draw_in_kernel"]]
    out = {
        "metric": "fused_kernel_smoke",
        "value": sum(1 for p in exact if p["winner_bitwise_match"]),
        "unit": "bitwise_parity_cases",
        "ok": report["ok"],
        "platform": report["platform"],
        "n_parity_cases": report["n_parity_cases"],
        "trajectory_identical": report["trajectory"]["identical"],
        "one_trace_per_bucket": report["recompilation"][
            "one_trace_per_bucket"
        ],
        "tiling_covered": report["tiling_100k"]["covered"],
        "errors": report["errors"],
        "artifact": out_path,
        "elapsed_s": round(time.time() - t0, 2),
    }
    print(json.dumps(out))
    return 0 if report["ok"] else 1


def failover_section(argv):
    """``python bench.py --failover [--quick]``: replica-plane warm
    failover smoke — the seeded failover campaign
    (scripts/failover_campaign.py) on CPU: two replica server processes
    share one root, 8 studies (one program bucket each) split across
    them by the consistent-hash ring, the owning replica is kill -9'd
    mid-campaign, and the survivor takes every orphaned study over
    claim → fsck-clean → recover → ledger pre-warm → serve; asserts
    every takeover ok+fsck_clean, ZERO request-path compiles on the
    migrated studies' first post-failover suggests (cold-counter delta
    over a quiescent probe window), zero lost/duplicated trials, and
    trajectories identical to the fault-free single-replica twin.  A
    quick run writes a separate file so CI can never clobber the
    committed full artifact (the PR 7 convention).  Prints ONE JSON
    line like the other bench sections."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    failover = _import_script("failover_campaign")
    quick = "--quick" in argv
    out_path = (
        "FAILOVER_SERVE.quick.json" if quick else "FAILOVER_SERVE.json"
    )
    if "--out" in argv:
        out_path = argv[argv.index("--out") + 1]
    t0 = time.time()
    report = failover.run_campaign(quick=quick)
    failover.write_report(report, out_path)
    out = {
        "metric": "failover_smoke",
        "value": report.get("n_migrated", 0),
        "unit": "migrated_studies",
        "ok": report["ok"],
        "victim": report.get("victim"),
        "takeovers_ok_and_fsck_clean": report.get(
            "all_takeovers_ok_and_fsck_clean"
        ),
        "cold_suggest_delta": report.get(
            "cold_suggest_delta_over_probe_window"
        ),
        "lost_trials": report.get("integrity", {}).get("lost_trials"),
        "duplicated_trials": report.get("integrity", {}).get(
            "duplicated_trials"
        ),
        "trajectories_match": report.get(
            "trajectories_match_fault_free"
        ),
        "fsck_clean": report.get("fsck_after_repair", {}).get("clean"),
        "errors": report["errors"],
        "artifact": out_path,
        "elapsed_s": round(time.time() - t0, 2),
    }
    print(json.dumps(out))
    return 0 if report["ok"] else 1


def control_section(argv):
    """``python bench.py --control [--quick]``: the closed-loop
    control-plane A/B (scripts/control_report.py) — the SAME seeded
    shifting-load profile against a static server and a --self-tune
    server.  Gates: self-tuned warm p99 no worse (platform-calibrated
    tolerance), zero SL6xx breach transitions in the self-tuned arm,
    every applied decision present in BOTH the decision journal and
    the knob-provenance journal, and the deterministic forced-breach
    fixture proving revert-to-static within one observation window.
    A quick run writes CONTROL_SERVE.quick.json so CI can never
    clobber the committed full artifact (the PR 7 convention).
    Prints ONE JSON line like the other bench sections."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    control_report = _import_script("control_report")
    quick = "--quick" in argv
    out_path = (
        "CONTROL_SERVE.quick.json" if quick else "CONTROL_SERVE.json"
    )
    if "--out" in argv:
        out_path = argv[argv.index("--out") + 1]
    t0 = time.time()
    profile = [
        dict(p) for p in control_report.serve_loadgen.DEFAULT_PROFILE
    ]
    window_s = 1.0
    if quick:
        for p in profile:
            p["trials"] = min(int(p["trials"]), 4)
        window_s = 0.5
    report = control_report.run_ab(profile=profile, window_s=window_s)
    with open(out_path, "w") as f:
        json.dump(report, f, indent=1)
        f.write("\n")
    out = {
        "metric": "control_serve_ab",
        "value": report["self_tuned"]["controller"].get("n_evaluated"),
        "unit": "evaluated_windows",
        "ok": report["ok"],
        "gates": report["gates"],
        "static_warm_p99_ms": report["static"]["suggest_warm_p99_ms"],
        "self_tuned_warm_p99_ms": (
            report["self_tuned"]["suggest_warm_p99_ms"]
        ),
        "n_applied_decisions": report["decision_audit"]["n_applied"],
        "breach_transitions": (
            report["self_tuned"]["breach_transitions"]
        ),
        "artifact": out_path,
        "elapsed_s": round(time.time() - t0, 2),
    }
    print(json.dumps(out))
    return 0 if report["ok"] else 1


def store_section(argv):
    """``python bench.py --store [--quick]``: storage-plane A/B — the
    per-doc layout vs the segmented append-only trial log
    (scripts/store_bench.py) at 10k and 100k trials (one small scale
    with ``--quick``).  Gates: >=10x fewer fsyncs per state transition
    (the B=64 group commit), zero O(N) scans on the segmented path,
    warm refresh replaying exactly the appended delta, cold-open
    recovery replaying the full log, lossless compaction.  Ratios and
    counts only — never absolute milliseconds.  A quick run writes a
    separate file so CI can never clobber the committed full artifact
    (the PR 7 convention).  Prints ONE JSON line like the other bench
    sections."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    store_bench = _import_script("store_bench")
    quick = "--quick" in argv
    out_path = "BENCH_STORE.quick.json" if quick else "BENCH_STORE.json"
    if "--out" in argv:
        out_path = argv[argv.index("--out") + 1]
    t0 = time.time()
    report = store_bench.run_campaign(quick=quick)
    store_bench.write_report(report, out_path)
    ratios = report["headline"]["fsync_ratio_doc_over_segment"]
    out = {
        "metric": "store_bench",
        "value": min(ratios.values()) if ratios else None,
        "unit": "x_fewer_fsyncs_per_transition",
        "ok": report["ok"],
        "fsync_ratio_doc_over_segment": ratios,
        "scales": report["scales"],
        "batch": report["batch"],
        "errors": report["errors"],
        "artifact": out_path,
        "elapsed_s": round(time.time() - t0, 2),
    }
    print(json.dumps(out))
    return 0 if report["ok"] else 1


def main():
    if "--control" in sys.argv[1:]:
        argv = [a for a in sys.argv[1:] if a != "--control"]
        return control_section(argv)
    if "--store" in sys.argv[1:]:
        argv = [a for a in sys.argv[1:] if a != "--store"]
        return store_section(argv)
    if "--slo" in sys.argv[1:]:
        argv = [a for a in sys.argv[1:] if a != "--slo"]
        return slo_section(argv)
    if "--study-health" in sys.argv[1:]:
        argv = [a for a in sys.argv[1:] if a != "--study-health"]
        return study_health_section(argv)
    if "--warmup" in sys.argv[1:]:
        argv = [a for a in sys.argv[1:] if a != "--warmup"]
        return warmup_section(argv)
    if "--device-profile" in sys.argv[1:]:
        argv = [a for a in sys.argv[1:] if a != "--device-profile"]
        return device_profile_section(argv)
    if "--sharded" in sys.argv[1:]:
        argv = [a for a in sys.argv[1:] if a != "--sharded"]
        return sharded_section(argv)
    if "--wallclock" in sys.argv[1:]:
        argv = [a for a in sys.argv[1:] if a != "--wallclock"]
        return wallclock_section(argv)
    if "--trace" in sys.argv[1:]:
        argv = [a for a in sys.argv[1:] if a != "--trace"]
        return trace_section(argv)
    if "--serve" in sys.argv[1:]:
        argv = [a for a in sys.argv[1:] if a != "--serve"]
        return serve_section(argv)
    if "--lint" in sys.argv[1:]:
        argv = [a for a in sys.argv[1:] if a != "--lint"]
        return lint_section(argv)
    if "--chaos-serve" in sys.argv[1:]:
        argv = [a for a in sys.argv[1:] if a != "--chaos-serve"]
        return chaos_serve_section(argv)
    if "--failover" in sys.argv[1:]:
        argv = [a for a in sys.argv[1:] if a != "--failover"]
        return failover_section(argv)
    if "--fused" in sys.argv[1:]:
        argv = [a for a in sys.argv[1:] if a != "--fused"]
        return fused_section(argv)
    if "--chaos" in sys.argv[1:]:
        argv = [a for a in sys.argv[1:] if a != "--chaos"]
        return chaos_section(argv)
    _ensure_live_backend()
    t_setup = time.time()
    import jax

    from hyperopt_tpu.algos import tpe, tpe_device

    platform = jax.devices()[0].platform
    cpu_bounds = _apply_cpu_bounds(platform)
    domain, trials = build_history_trials()
    setup_s = time.time() - t_setup

    smoke_scorer, smoke_err, smoke_fma = _tpu_smoke()
    rtt = _measure_rtt()
    cap_b = _derived_cap_b()

    # --- production driver loop: suggest with growing history ---------
    def one_suggest(i):
        tid = N_HISTORY + i
        docs = tpe.suggest(
            [tid], domain, trials, i, n_EI_candidates=N_EI_CANDIDATES
        )
        return docs[0]

    rng = np.random.default_rng(1)

    def complete(doc):
        # close the loop: the suggested trial completes and joins history
        from hyperopt_tpu.base import JOB_STATE_DONE, STATUS_OK

        doc["state"] = JOB_STATE_DONE
        doc["result"] = {"status": STATUS_OK, "loss": float(rng.standard_normal())}
        trials._insert_trial_docs([doc])
        trials.refresh()

    t0 = time.time()
    # two warmup iterations: the first compiles the suggest program, the
    # second the steady-state append program (a retrace landing inside
    # the timed window would inflate host_transfer_ms ~25x)
    complete(one_suggest(0))
    complete(one_suggest(1))
    warmup_s = time.time() - t0

    dh = tpe_device.device_history_for(trials, domain.space)
    sync0, bytes0 = dh.sync_time, dh.bytes_uploaded
    t_suggest = 0.0
    for i in range(2, TIMED_SUGGESTS + 2):
        t0 = time.perf_counter()
        doc = one_suggest(i)
        t_suggest += time.perf_counter() - t0
        complete(doc)
    xla_per_suggest = t_suggest / TIMED_SUGGESTS
    host_transfer_ms = (dh.sync_time - sync0) / TIMED_SUGGESTS * 1e3
    host_bytes = (dh.bytes_uploaded - bytes0) / TIMED_SUGGESTS
    suggests_per_sec = 1.0 / xla_per_suggest

    flops = _scorer_flops(dh, N_EI_CANDIDATES)

    # --- batched suggest (JaxTrials production mode): k trials per
    # dispatch amortizes the per-call host/tunnel overhead -------------
    kb = int(os.environ.get("BENCH_BATCH_K", 32))
    _ = tpe.suggest(
        [N_HISTORY + 10_000 + i for i in range(kb)], domain, trials, 0,
        n_EI_candidates=N_EI_CANDIDATES,
    )  # warm
    t0 = time.perf_counter()
    breps = cpu_bounds.get("breps", int(os.environ.get("BENCH_BATCH_REPS", 5)))
    for r in range(breps):
        tpe.suggest(
            [N_HISTORY + 20_000 + r * kb + i for i in range(kb)],
            domain, trials, r, n_EI_candidates=N_EI_CANDIDATES,
        )
    batched_per = (time.perf_counter() - t0) / breps
    batched_rate = kb / batched_per

    # --- device-plane scorer throughput (tunnel-safe, amortized) ------
    ab, device_ei_rate = _device_scorer_bench(rtt, cap_b, platform)
    # per-suggest pair-scorer EI evals: continuous non-quantized families
    # only (quantized ones take the exact CDF-bucket path, not the pair
    # scorer), real components only (history + prior, not padding)
    k_real = (min(LF, N_HISTORY) + 1) + (N_HISTORY + 1)
    suggest_ei_evals = sum(
        fam.L * N_EI_CANDIDATES * k_real
        for fam in dh.families.values()
        if fam.key[0] == "cont" and not fam.quantized
    )
    dev_fields = device_headline_fields(
        _scorer_cost(dh, N_EI_CANDIDATES, scorer=smoke_scorer),
        suggest_ei_evals, device_ei_rate, platform, smoke_scorer,
    )

    # --- numpy baseline (reference-equivalent compute) ----------------
    nrng = np.random.default_rng(0)
    t0 = time.time()
    reps = 2
    np_ei = 0
    for _ in range(reps):
        _, np_ei = numpy_reference_suggest(trials.history, nrng)
    np_per_suggest = (time.time() - t0) / reps
    np_ei_rate = np_ei / np_per_suggest

    out = {
        "metric": "tpe_candidate_EI_evals_per_sec_10k_history",
        # null contract: an unmeasured headline is null + a reason,
        # never a 0.0 placeholder (see device_headline_fields)
        "value": round(device_ei_rate, 1) if device_ei_rate > 0 else None,
        "unit": "EI_evals/s",
        "vs_baseline": (
            round(device_ei_rate / np_ei_rate, 1)
            if device_ei_rate > 0 and np_ei_rate else None
        ),
        "baseline_kind": (
            "numpy reimplementation of reference compute at identical "
            "shapes (reference code unobtainable offline); north star is "
            ">=1000x this ratio"
        ),
        "platform": platform,
        "n_history": N_HISTORY,
        "n_labels": N_LABELS,
        "n_EI_candidates": N_EI_CANDIDATES,
        "suggests_per_sec_driver_loop": round(suggests_per_sec, 3),
        "xla_ms_per_suggest_driver_loop": round(xla_per_suggest * 1e3, 3),
        "suggests_per_sec_batched": round(batched_rate, 2),
        "batched_k": kb,
        "tunnel_rtt_ms": round(rtt * 1e3, 2),
        "numpy_baseline_ms_per_suggest": round(np_per_suggest * 1e3, 3),
        "numpy_baseline_ei_evals_per_sec": round(np_ei_rate, 1),
        "host_transfer_ms_per_suggest": round(host_transfer_ms, 4),
        "host_bytes_per_suggest": int(host_bytes),
        "device_history_rebuilds": dh.full_rebuilds,
        "scorer_matmul_gflops_per_suggest": round(flops / 1e9, 2),
        **dev_fields,
        "smoke": {
            "scorer": smoke_scorer,
            "precision_max_err": round(smoke_err, 6),
            "pallas_fma_default": smoke_fma[0],
            "pallas_fma_default_unbatched": smoke_fma[1],
            # the probe's measured basis per entry point — both values
            # route through the ONE resolve_fma resolver, and the basis
            # explains any per-kernel disagreement (ISSUE-14 satellite)
            "pallas_fma_basis": smoke_fma[2],
        },
        "scorer_ab": ab,
        "compile_warmup_s": round(warmup_s, 2),
        "setup_s": round(setup_s, 2),
    }
    print(json.dumps(out))


if __name__ == "__main__":
    sys.exit(main())
