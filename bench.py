"""Headline benchmark: TPE suggestions/sec at a 10k-trial history.

BASELINE.md metric: "TPE suggestions/sec @ 10k-trial history" with the
north-star of ≥1000× the CPU reference's candidate-EI evaluations/sec.
The reference (gsmafra/hyperopt) is pure numpy on CPU and is not installed
in this image, so the baseline is a faithful numpy REIMPLEMENTATION of the
same per-suggest computation (adaptive-Parzen fit of l/g per label +
O(candidates × history) log-density scoring) — the exact math this
framework runs as fused XLA kernels, at the same n_EI_candidates.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N, ...extras}

Runs on the default JAX platform (the real TPU chip under axon; CPU
elsewhere).  Do not run under tests/conftest.py (that forces CPU).
"""

import json
import os
import sys
import time

import numpy as np

# Headline config (BASELINE.md); env knobs exist for quick smoke runs on
# CPU (e.g. BENCH_N_HISTORY=1000 BENCH_N_CAND=256 BENCH_TIMED=5).
N_HISTORY = int(os.environ.get("BENCH_N_HISTORY", 10_000))
N_LABELS = 5
N_EI_CANDIDATES = int(os.environ.get("BENCH_N_CAND", 8_192))
GAMMA = 0.25
LF = 25
TIMED_SUGGESTS = int(os.environ.get("BENCH_TIMED", 30))


def build_history_trials():
    """10k completed trials over a 5-label mixed space (doc-building cost
    excluded from timing)."""
    from hyperopt_tpu import Trials, hp
    from hyperopt_tpu.base import JOB_STATE_DONE, STATUS_OK, Domain

    space = {
        "lr": hp.loguniform("lr", np.log(1e-5), np.log(1.0)),
        "momentum": hp.uniform("momentum", 0.0, 1.0),
        "width": hp.quniform("width", 32, 1024, 32),
        "sigma": hp.lognormal("sigma", 0.0, 1.0),
        "z": hp.normal("z", 0.0, 3.0),
    }
    domain = Domain(lambda c: 0.0, space)
    rng = np.random.default_rng(0)
    vals, _ = domain.space.sample_batch(0, N_HISTORY)
    losses = rng.standard_normal(N_HISTORY)
    docs = []
    for i in range(N_HISTORY):
        misc = {
            "tid": i,
            "cmd": None,
            "idxs": {k: [i] for k in vals},
            "vals": {k: [float(vals[k][i])] for k in vals},
        }
        docs.append(
            {
                "tid": i,
                "spec": None,
                "result": {"status": STATUS_OK, "loss": float(losses[i])},
                "misc": misc,
                "state": JOB_STATE_DONE,
                "owner": None,
                "book_time": None,
                "refresh_time": None,
                "exp_key": None,
            }
        )
    trials = Trials()
    trials._insert_trial_docs(docs)
    trials.refresh()
    return domain, trials


# ---------------------------------------------------------------------
# numpy reference implementation (CPU-hyperopt-equivalent compute)
# ---------------------------------------------------------------------


def _np_parzen(obs, prior_mu, prior_sigma, lf=LF):
    n = len(obs)
    if n == 0:
        return np.array([1.0]), np.array([prior_mu]), np.array([prior_sigma])
    order = np.argsort(obs)
    srtd = obs[order]
    pos = int(np.searchsorted(srtd, prior_mu))
    mus = np.insert(srtd, pos, prior_mu)
    k = n + 1
    sigma = np.zeros(k)
    if k > 1:
        gaps_l = np.diff(mus, prepend=mus[0])
        gaps_r = np.diff(mus, append=mus[-1])
        sigma = np.maximum(gaps_l, gaps_r)
        sigma[0] = mus[1] - mus[0]
        sigma[-1] = mus[-1] - mus[-2]
    sigma = np.clip(sigma, prior_sigma / min(100.0, 1.0 + k), prior_sigma)
    sigma[pos] = prior_sigma
    if lf and n > lf:
        w = np.concatenate([np.linspace(1.0 / n, 1.0, n - lf), np.ones(lf)])
    else:
        w = np.ones(n)
    w = w[order]
    weights = np.insert(w, pos, 1.0)
    weights /= weights.sum()
    return weights, mus, sigma


def _np_gmm_lpdf(x, w, mu, sigma):
    # O(C x K) — the reference's hot loop
    mahal = ((x[:, None] - mu[None, :]) / sigma[None, :]) ** 2
    comp = -0.5 * mahal - np.log(sigma * np.sqrt(2 * np.pi))[None, :] + np.log(w)[None, :]
    m = comp.max(axis=1, keepdims=True)
    return (m[:, 0]) + np.log(np.exp(comp - m).sum(axis=1))


def numpy_reference_suggest(hist, rng, n_cand=N_EI_CANDIDATES):
    losses = hist.losses
    n = len(losses)
    n_below = min(int(np.ceil(GAMMA * np.sqrt(n))), LF)
    order = np.argsort(losses, kind="stable")
    below_tids = hist.loss_tids[order[:n_below]]
    out = {}
    for label, tids in hist.idxs.items():
        obs = np.asarray(hist.vals[label], dtype=np.float64)
        mask = np.isin(tids, below_tids)
        b, a = obs[mask], obs[~mask]
        wb, mb, sb = _np_parzen(b, float(obs.mean()), float(obs.std() + 1e-3))
        wa, ma, sa = _np_parzen(a, float(obs.mean()), float(obs.std() + 1e-3))
        comp = rng.choice(len(wb), size=n_cand, p=wb)
        cand = rng.normal(mb[comp], sb[comp])
        score = _np_gmm_lpdf(cand, wb, mb, sb) - _np_gmm_lpdf(cand, wa, ma, sa)
        out[label] = cand[np.argmax(score)]
    return out


def _ensure_live_backend():
    """Guard against a wedged TPU tunnel: probe device init in a throwaway
    subprocess; on hang/failure re-exec this bench on CPU.  (Setting
    JAX_PLATFORMS alone is not enough — the axon sitecustomize overrides
    the config in every process — so the axon env trigger is removed.)"""
    import subprocess

    if os.environ.get("BENCH_BACKEND_PROBED"):
        return
    try:
        subprocess.run(
            [sys.executable, "-c", "import jax; jax.devices()"],
            timeout=180,
            capture_output=True,
            check=True,
        )
        os.environ["BENCH_BACKEND_PROBED"] = "1"
        return
    except (subprocess.TimeoutExpired, subprocess.CalledProcessError):
        pass
    print("bench: TPU backend unreachable, falling back to CPU", file=sys.stderr)
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)  # disables axon registration
    env["JAX_PLATFORMS"] = "cpu"
    env["BENCH_BACKEND_PROBED"] = "1"
    os.execve(sys.executable, [sys.executable] + sys.argv, env)


def main():
    _ensure_live_backend()
    t_setup = time.time()
    import jax

    from hyperopt_tpu.algos import tpe

    platform = jax.devices()[0].platform
    domain, trials = build_history_trials()
    hist = trials.history
    setup_s = time.time() - t_setup

    # --- TPU/XLA path -------------------------------------------------
    def one_suggest(seed):
        return tpe.suggest(
            [N_HISTORY + seed],
            domain,
            trials,
            seed,
            n_EI_candidates=N_EI_CANDIDATES,
        )

    t0 = time.time()
    one_suggest(0)  # compile warmup
    warmup_s = time.time() - t0

    t0 = time.time()
    for i in range(TIMED_SUGGESTS):
        one_suggest(i + 1)
    xla_per_suggest = (time.time() - t0) / TIMED_SUGGESTS
    suggests_per_sec = 1.0 / xla_per_suggest
    # candidate-EI evaluations per second (the north-star counter):
    # each suggest scores n_cand candidates against ~N_HISTORY components
    # for l and g across N_LABELS labels
    ei_evals_per_sec = N_EI_CANDIDATES * N_LABELS / xla_per_suggest

    # --- numpy baseline (reference-equivalent compute) ----------------
    rng = np.random.default_rng(0)
    t0 = time.time()
    reps = 3
    for _ in range(reps):
        numpy_reference_suggest(hist, rng)
    np_per_suggest = (time.time() - t0) / reps

    vs_baseline = np_per_suggest / xla_per_suggest

    print(
        json.dumps(
            {
                "metric": "tpe_suggestions_per_sec_10k_history",
                "value": round(suggests_per_sec, 3),
                "unit": "suggest/s",
                "vs_baseline": round(vs_baseline, 2),
                "platform": platform,
                "n_history": N_HISTORY,
                "n_labels": N_LABELS,
                "n_EI_candidates": N_EI_CANDIDATES,
                "xla_ms_per_suggest": round(xla_per_suggest * 1e3, 3),
                "numpy_baseline_ms_per_suggest": round(np_per_suggest * 1e3, 3),
                "candidate_EI_evals_per_sec": round(ei_evals_per_sec, 1),
                "compile_warmup_s": round(warmup_s, 2),
                "setup_s": round(setup_s, 2),
            }
        )
    )


if __name__ == "__main__":
    sys.exit(main())
